"""Async buffered aggregation: FedBuff-style rounds over the federated
runtime (ROADMAP item 3; Nguyen et al. 2022, "Federated Learning with
Buffered Asynchronous Aggregation").

Why this exists
---------------
The runtime is lockstep: every round blocks on its full client cohort,
so round capacity is capped by the slowest simulated cohort and there is
no story for stragglers, churn, or partial participation. Production
federated systems aggregate asynchronously — clients upload whenever
they finish, the server folds updates into a buffer, discounts stale
ones, and commits when the buffer reaches a goal size. The FetchSGD
lineage makes this unusually cheap here: the Count Sketch is LINEAR, so
cohort uploads landing out of order merge into one sketch buffer by
pure addition, and the server's momentum/error-feedback state stays
"virtual" exactly as the synchronous server does (PAPER.md §2.1/§2.3).

What runs where
---------------
:class:`AsyncAggregator` is the host-side controller, generalizing
core/pipeline.py's prefetch thread into a bounded in-flight pool over
SERVER work:

- ``dispatch`` (every driver tick): one cohort (a sampler round of
  ``num_workers`` clients) is computed against the CURRENT weights via
  ``FedRuntime.cohort`` — the client half of the synchronous round,
  stopping before the server update. The payload (the unnormalized
  transmitted-space sum + datum count) stays on device; up to
  ``max_inflight`` (K) payloads are held. jax's async dispatch means
  the host loop never blocks on cohort compute.
- ``land`` (simulated arrival order, data/scenarios.py): the cohort's
  sum merges into the ``FedState.async_buffer`` by staleness-weighted
  addition. Staleness s = commits between the cohort's dispatch and its
  merge; the weight is ``staleness_weight(cfg.staleness_discount, s,
  cfg.staleness_alpha)`` — discounting happens in COMPRESSED/EF space
  (a scalar times a linear sketch is the sketch of the scaled
  gradient, so the discount commutes with decoding).
- ``commit`` (every ``buffer_goal`` (M) merged cohorts, or at the
  epoch-boundary flush): ``FedRuntime.commit`` normalizes the buffer by
  its RAW datum count (FedBuff's divide-by-K: the denominator ignores
  the discounts, so a stale cohort's contribution is genuinely
  attenuated by its weight instead of the discount cancelling) and runs
  the mode's UNCHANGED server momentum+EF step (core/server.py), then
  zeroes the buffer. The FedState ``step`` counter counts commits — the
  server version.

Sync equivalence
----------------
With K=1, M=1 and no scenario latency every cohort lands and commits in
its own tick with staleness 0 (weight exactly 1.0, all discount rules),
and the first-merge path swaps the cohort sum into the empty buffer
without arithmetic — the composition cohort→merge→commit is
bit-identical to the fused synchronous round (asserted per sound mode by
``__graft_entry__.dryrun_multichip`` and tests/test_async_agg.py).
One scope caveat: the split steps advance ``state.rng`` differently
from the fused round (a W+1 split at dispatch plus a 2-split at commit,
vs one W+2 split), so the bitwise claim covers configurations that
CONSUME no per-round randomness — which is every sound mode without DP.
Async + DP remains sound (worker noise/clip are per-client ops before
the sum; server noise draws at commit), it just follows a different —
still deterministic — noise stream than the lockstep run.

Wire composition
----------------
The quantized sketch wire (``--wire_dtype``; ops/wire.py) composes for
free: the cohort step applies the wire BEFORE its payload leaves the
executable (bf16 rounding or int8 quantize->all_to_all->dequantize on
the collective, per-client round-trips single-device), so by the time a
cohort sum reaches :meth:`AsyncAggregator` it is an ordinary f32 array
— buffer merges stay pure f32 additions and the staleness discount
multiplies dequantized values (a scalar times the dequantized table is
the dequantization of nothing the wire ever carried — the discount is
server-side, after the wire, exactly like the sync normalization). The
int8 rounding draws key off the server version (``state.step``), which
K=1/M=1 shares with the sync round — the bit-identity gate covers the
int8 arm in ``__graft_entry__._wire_gate``.

Soundness
---------
Buffered merging is sound exactly when the server consumes the cohort
uploads ONLY through their weighted sum. Modes with per-client
persistent rows break that: local momentum rows are masked with the
SAME round's server support (momentum factor masking), and local error
rows / topk_down client weights are written at dispatch from state the
commit hasn't produced yet. :func:`validate_async_combo` fails fast on
those combinations — see the README soundness matrix.
"""

from __future__ import annotations

import bisect
import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from commefficient_tpu.config import FedConfig
from commefficient_tpu.faults import maybe_fault

DISCOUNT_RULES = ("none", "poly", "exp")


def staleness_weight(rule: str, staleness: float, alpha: float = 0.5
                     ) -> float:
    """Merge weight of a cohort ``staleness`` commits old.

    - ``none``: 1 (plain FedBuff averaging);
    - ``poly``: (1+s)^-alpha — alpha 0.5 is FedBuff's 1/sqrt(1+s);
    - ``exp``: exp(-alpha*s).

    Every rule returns EXACTLY 1.0 at s=0 (the sync-equivalence
    contract) and decreases monotonically in s.
    """
    s = float(staleness)
    if s < 0:
        raise ValueError(f"staleness must be >= 0, got {s}")
    if rule == "none":
        return 1.0
    if rule == "poly":
        return float((1.0 + s) ** (-float(alpha)))
    if rule == "exp":
        return float(math.exp(-float(alpha) * s))
    raise ValueError(f"unknown staleness discount {rule!r}; "
                     f"choices: {DISCOUNT_RULES}")


def _split_round_problems(cfg: FedConfig) -> List[str]:
    """Why a configuration cannot run its round as separate client/server
    executables (the cohort step carries no per-client persistent-row or
    topk_down plumbing — shared by --async_agg and --decode_overlap)."""
    problems: List[str] = []
    if cfg.needs_client_velocities:
        problems.append(
            "local_momentum > 0 keeps per-client velocity rows that the "
            "synchronous round masks with the SAME round's server support "
            "(momentum factor masking) — the split client block finishes "
            "before that support exists, so the masking semantics cannot "
            "be reproduced. Use local_momentum 0 (rely on "
            "--virtual_momentum, which lives in server state and splits "
            "soundly)")
    if cfg.needs_client_errors:
        problems.append(
            "error_type=local keeps per-client error rows written at "
            "dispatch; the split round's client block has no row "
            "plumbing (and under buffering the rows would accumulate "
            "against interleaved server versions the synchronous rule "
            "never sees). Use error_type none (local_topk) or virtual "
            "(sketch/true_topk — virtual EF lives in server state and "
            "splits soundly)")
    if cfg.do_topk_down:
        problems.append(
            "--topk_down keeps per-client stale weight vectors updated "
            "at dispatch from the current server weights — the split "
            "client block has no weight-row plumbing (and under "
            "buffering a client's record diverges from what it actually "
            "downloaded). Drop --topk_down")
    return problems


def validate_async_combo(cfg: FedConfig) -> None:
    """Reject mode combinations where buffered merge is unsound.

    The buffer consumes cohort uploads only through their weighted sum;
    any per-client persistent state written at dispatch from commit-time
    information cannot be reproduced out of order. Mirrors the fail-fast
    contract of core/server.validate_mode_combo."""
    if not cfg.async_agg:
        return
    problems = _split_round_problems(cfg)
    if problems:
        raise ValueError(
            "--async_agg: buffered merge is unsound for this "
            "configuration:\n  " + "\n  ".join(problems))


def validate_overlap_combo(cfg: FedConfig) -> None:
    """--decode_overlap's fail-fast twin of :func:`validate_async_combo`:
    the split round shares the cohort step, so the same per-client
    persistent-state combinations are out (config.py already rejects
    --decode_overlap together with --async_agg)."""
    if not cfg.decode_overlap:
        return
    problems = _split_round_problems(cfg)
    if problems:
        raise ValueError(
            "--decode_overlap: splitting the round into client and "
            "server-decode executables is unsound for this "
            "configuration:\n  " + "\n  ".join(problems))


def reconcile_resumed_state(state, runtime) -> Tuple[Any, List[str]]:
    """Make a restored FedState consistent with this runtime's async
    configuration. Returns (state, messages-to-print).

    - async run resuming a checkpoint WITHOUT buffer fields (pre-async
      vintage, reachable only past the restore-time meta guard): the
      buffer starts EMPTY — safe, nothing double-counts.
    - async run resuming a NON-EMPTY buffer (a mid-epoch postmortem
      bundle): the buffer is LOUDLY restarted. The epoch replays from
      its boundary, so its cohorts will be recomputed — restoring the
      buffer would double-count every one of them.
    - sync run resuming an async-mode checkpoint: the buffer fields are
      dropped (warning if non-empty) so the state matches the sync
      runtime's template.
    """
    import jax.numpy as jnp

    msgs: List[str] = []
    if runtime.cfg.async_agg:
        if state.async_buffer is None:
            tmpl = runtime._state_template()
            state = state.replace(
                async_buffer=jnp.zeros(tmpl.async_buffer.shape,
                                       jnp.float32),
                async_buffer_n=jnp.zeros((), jnp.float32))
            msgs.append(
                "async buffer initialized EMPTY: the checkpoint predates "
                "async buffered aggregation (no buffer state to restore; "
                "nothing double-counts)")
        else:
            n = float(np.asarray(state.async_buffer_n))
            if n > 0:
                state = state.replace(
                    async_buffer=jnp.zeros_like(state.async_buffer),
                    async_buffer_n=jnp.zeros_like(state.async_buffer_n))
                msgs.append(
                    f"resume mid-buffer: RESTARTING the partial async "
                    f"buffer ({n:.0f} buffered datums discarded). The "
                    "epoch replays from its boundary, so keeping the "
                    "buffer would double-count its cohorts")
    elif state.async_buffer is not None:
        n = float(np.asarray(state.async_buffer_n)) \
            if state.async_buffer_n is not None else 0.0
        if n > 0:
            msgs.append(
                f"discarding a non-empty async buffer ({n:.0f} datums) "
                "from an async-mode checkpoint resumed synchronously")
        state = state.replace(async_buffer=None, async_buffer_n=None)
    return state, msgs


class _InFlight:
    """One dispatched-but-unlanded cohort: device payload + bookkeeping."""

    __slots__ = ("cohort", "version", "arrival", "sum", "n_total",
                 "results", "n_valid")

    def __init__(self, cohort, version, arrival, payload):
        self.cohort = int(cohort)
        self.version = int(version)       # server commits at dispatch
        self.arrival = float(arrival)     # simulated arrival tick
        self.sum = payload["sum"]         # device array, dropped at merge
        self.n_total = payload["n_total"]
        self.results = payload["results"]
        self.n_valid = payload["n_valid"]

    def __lt__(self, other):              # bisect.insort ordering
        return (self.arrival, self.cohort) < (other.arrival, other.cohort)


def commit_loss(rec: Dict[str, Any]) -> Optional[float]:
    """Datum-weighted mean dispatch loss of a commit's merged cohorts.
    Syncs the cohort result refs to host — call only at the telemetry
    record cadence (the fetch-once discipline of the driver loop)."""
    num = den = 0.0
    for res0, n_valid in rec.get("loss_refs", ()):
        r = np.asarray(res0, np.float64)
        n = np.asarray(n_valid, np.float64)
        num += float((r * n).sum())
        den += float(n.sum())
    if den <= 0:
        return None
    v = num / den
    return v if math.isfinite(v) else None


class AsyncAggregator:
    """Bounded in-flight pool + staleness-weighted buffer over a
    FedRuntime built with ``cfg.async_agg``.

    Driver contract (cv_train.train): one :meth:`step` per sampler
    round; at the epoch boundary one :meth:`flush` (land everything,
    commit any partial buffer) so epochs — and therefore checkpoints —
    never straddle an open buffer. ``step``/``flush`` return the list of
    commit records produced, each carrying the merged cohorts' measured
    staleness/discounts plus device refs for the ``async_round``
    telemetry event.
    """

    def __init__(self, runtime, scenario=None, *,
                 max_inflight: Optional[int] = None,
                 buffer_goal: Optional[int] = None,
                 discount: Optional[str] = None,
                 alpha: Optional[float] = None):
        cfg = runtime.cfg
        if not cfg.async_agg:
            raise ValueError("AsyncAggregator needs a runtime built with "
                             "cfg.async_agg=True (the cohort/commit steps "
                             "are only jitted then)")
        validate_async_combo(cfg)
        sc_plan = getattr(scenario, "adversary", None)
        rt_plan = getattr(runtime, "adversary_plan", None)
        if sc_plan is not None and rt_plan is not None:
            # the scenario's per-cohort adversary annotation
            # (CohortFate.adversary) and the universe mask the jitted
            # round actually applies are two AdversaryPlan instances
            # that must describe the SAME assignment — a seed/frac
            # mismatch would make the telemetry/ledger view silently
            # diverge from the injected reality
            a = (sc_plan.kind, sc_plan.frac, sc_plan.seed, sc_plan.scale)
            b = (rt_plan.kind, rt_plan.frac, rt_plan.seed, rt_plan.scale)
            if a != b:
                raise ValueError(
                    f"scenario adversary plan {a} disagrees with the "
                    f"runtime's {b}: build both from the same FedConfig "
                    "(make_scenario/make_adversary with matching seeds)")
        self.runtime = runtime
        self.scenario = scenario
        self.max_inflight = int(max_inflight if max_inflight is not None
                                else cfg.max_inflight)
        self.buffer_goal = int(buffer_goal if buffer_goal is not None
                               else cfg.buffer_goal)
        self.discount = (discount if discount is not None
                         else cfg.staleness_discount)
        self.alpha = float(alpha if alpha is not None
                           else cfg.staleness_alpha)
        assert self.max_inflight >= 1 and self.buffer_goal >= 1
        self._inflight: List[_InFlight] = []      # sorted by (arrival, id)
        self._pending: List[Dict[str, Any]] = []  # merged, uncommitted
        self.commits = 0          # host mirror of the server version delta
        self.dispatched = 0
        self.dropped = 0
        self.merged = 0
        self.staleness_max_seen = 0
        self._staleness_sum = 0.0

    # ------------------------------------------------------------- observers

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    @property
    def pending(self) -> int:
        return len(self._pending)

    @property
    def staleness_mean_seen(self) -> float:
        return self._staleness_sum / max(self.merged, 1)

    # ----------------------------------------------------------------- steps

    def step(self, state, rnd, global_round: int, batch, lr
             ) -> Tuple[Any, Optional[Dict[str, Any]],
                        List[Dict[str, Any]]]:
        """One driver tick: land overdue cohorts, free a pool slot if
        full, apply the scenario fate, dispatch this tick's cohort, and
        land zero-latency arrivals. Returns ``(state, cohort_metrics,
        commit_records)``; ``cohort_metrics`` is None for a dropped
        cohort (no compute happened)."""
        commits: List[Dict[str, Any]] = []
        tick = int(global_round)
        state = self._land_due(state, tick, lr, commits)
        mask_np = np.asarray(rnd.mask)
        fate = (self.scenario.fate(tick, mask_np,
                                   client_ids=rnd.client_ids)
                if self.scenario is not None else None)
        if fate is not None and fate.dropped:
            # decided BEFORE the pool-full wait: a dropped cohort never
            # needs a slot, so it must not force an in-flight cohort to
            # land early (that would skew the measured staleness)
            self.dropped += 1
            return state, None, commits
        while len(self._inflight) >= self.max_inflight:
            # the pool is full: the simulated dispatch waits for the
            # earliest in-flight cohort, exactly like a real bounded
            # upload queue
            state = self._land_earliest(state, lr, commits)
        eff_mask = fate.mask if fate is not None else mask_np
        state, payload = self.runtime.cohort(
            state, rnd.client_ids, batch, eff_mask, lr)
        # crash-matrix kill-point: the pool holds in-flight cohorts and
        # this tick's dispatch just happened — a death here must resume
        # bit-identically (the epoch replays; the buffer was never
        # checkpointed open, see reconcile_resumed_state)
        maybe_fault("async_pool", tick)
        self.dispatched += 1
        latency = float(fate.latency) if fate is not None else 0.0
        bisect.insort(self._inflight,
                      _InFlight(tick, self.commits, tick + latency,
                                payload))
        state = self._land_due(state, tick, lr, commits)
        metrics = {
            "results": payload["results"],
            "n_valid": payload["n_valid"],
            "download_bytes": payload["download_bytes"],
            "upload_bytes": payload["upload_bytes"],
            "signals": None,
            "layer_signals": None,
            "client_stats": payload["client_stats"],
            # robustness channel (core/runtime._cohort_step): the
            # defense-event scalars and the quarantine ledger's
            # per-client finite flags ride the cohort payload — the
            # driver's defense wiring is path-agnostic
            "defense": payload["defense"],
            "client_finite": payload["client_finite"],
            # host-resident effective participation for the ledger (the
            # scenario may have masked slots out of this cohort)
            "participation": (np.asarray(rnd.client_ids),
                              eff_mask.sum(axis=1)),
            # the scenario's per-slot adversary annotation
            # (CohortFate.adversary): the driver's defense event counts
            # injections from the SAME draw the dispatch saw instead of
            # re-deriving it against the ledger's view of the round
            "adversary_slots": (fate.adversary if fate is not None
                                else None),
        }
        return state, metrics, commits

    def flush(self, state, lr) -> Tuple[Any, List[Dict[str, Any]]]:
        """Epoch-boundary drain: land every in-flight cohort (in arrival
        order) and commit whatever the buffer holds — a partial commit
        below ``buffer_goal`` is flagged ``partial`` in its record, so
        no open buffer ever crosses an epoch (or reaches a checkpoint)."""
        commits: List[Dict[str, Any]] = []
        while self._inflight:
            state = self._land_earliest(state, lr, commits)
        if self._pending:
            state, rec = self._commit(state, lr, partial=True)
            commits.append(rec)
        return state, commits

    # -------------------------------------------------------------- internals

    def _land_due(self, state, tick: int, lr, commits) -> Any:
        while self._inflight and self._inflight[0].arrival <= tick:
            state = self._land_earliest(state, lr, commits)
        return state

    def _land_earliest(self, state, lr, commits) -> Any:
        item = self._inflight.pop(0)
        staleness = self.commits - item.version
        weight = staleness_weight(self.discount, staleness, self.alpha)
        if not self._pending and weight == 1.0:
            # empty buffer, weight 1: swap the cohort sum in directly —
            # no arithmetic, the bitwise sync-equivalence path
            state = self.runtime.merge_first(state, item.sum, item.n_total)
        else:
            state = self.runtime.merge(state, item.sum, item.n_total,
                                       weight)
        # the buffer owns (and the next merge/commit donates) these
        # device arrays now — drop the refs so nothing reads a donated
        # buffer later
        item.sum = item.n_total = None
        self.merged += 1
        self._staleness_sum += staleness
        self.staleness_max_seen = max(self.staleness_max_seen, staleness)
        self._pending.append({
            "cohort": item.cohort,
            "staleness": int(staleness),
            "weight": float(weight),
            "loss_ref": (item.results[0], item.n_valid),
        })
        if len(self._pending) >= self.buffer_goal:
            state, rec = self._commit(state, lr, partial=False)
            commits.append(rec)
        return state

    def _commit(self, state, lr, partial: bool
                ) -> Tuple[Any, Dict[str, Any]]:
        state, m = self.runtime.commit(state, lr)
        self.commits += 1
        pend, self._pending = self._pending, []
        st = [p["staleness"] for p in pend]
        ws = [p["weight"] for p in pend]
        rec = {
            "round": self.commits,
            "n_cohorts": len(pend),
            "cohorts": [p["cohort"] for p in pend],
            "staleness_mean": float(np.mean(st)),
            "staleness_max": int(max(st)),
            "discount_mean": float(np.mean(ws)),
            "discount_min": float(min(ws)),
            "partial": bool(partial),
            "buffer_n": m["buffer_n"],        # device scalar refs: sync
            "update_norm": m["update_norm"],  # only at the record cadence
            "error_norm": m["error_norm"],
            "velocity_norm": m["velocity_norm"],
            "loss_refs": [p["loss_ref"] for p in pend],
        }
        return state, rec
