"""Host-side quarantine ledger: bounded re-admission for clients whose
uploads went nonfinite (``--nonfinite_action quarantine``).

The device side of the recovery story lives in the jitted round
(core/runtime.py): a nonfinite per-client update is zeroed out of the
aggregate THERE, so the global model is protected even before the host
learns anything. This ledger is the slower control loop on top — it
reads the round's per-client finite flags (one (W,)-bool fetch per
round, the only host-sync cost of quarantine mode) and decides which
clients the NEXT rounds should not even dispatch:

- a nonfinite upload is a **strike**: the client is benched for
  ``backoff`` rounds (its sampled slots are masked out via
  data/fed_sampler.mask_blocked — static shapes preserved, zero data);
- after the backoff it is **re-admitted** and retried — transient
  failures (a bad batch, an fp16 overflow on one round) recover;
- after ``strikes`` strikes it is **permanently ejected** — a client
  that keeps producing NaNs is broken or hostile, and retrying it
  forever would spend ``backoff`` rounds of its slot on nothing.

Strikes only accrue on rounds the client actually participated in (a
benched client cannot strike again — its mask is zeroed), so
``strikes=3`` means three separate failed retries, not three rounds of
one failure. Dependency-free and deterministic: state is a pure
function of the observed (round, client, finite) sequence, so a
replayed run reproduces the same bench/eject decisions.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Set


class QuarantineLedger:
    def __init__(self, backoff: int = 8, strikes: int = 3):
        if backoff < 1:
            raise ValueError(f"backoff must be >= 1, got {backoff}")
        if strikes < 1:
            raise ValueError(f"strikes must be >= 1, got {strikes}")
        self.backoff = int(backoff)
        self.max_strikes = int(strikes)
        self.strikes: Dict[int, int] = {}       # client -> strike count
        self._until: Dict[int, int] = {}        # client -> benched until rnd
        self.ejected: Set[int] = set()
        self.total_strikes = 0

    # ------------------------------------------------------------ observing

    def observe(self, rnd: int, client_ids, finite) -> List[int]:
        """Record one round's per-slot finite flags; returns the clients
        struck THIS round. ``finite`` is the round's (W,) bool vector
        (False = the client's upload was zeroed on device)."""
        struck: List[int] = []
        for cid, fin in zip(list(client_ids), list(finite)):
            if fin:
                continue
            cid = int(cid)
            if cid in self.ejected:
                continue
            n = self.strikes.get(cid, 0) + 1
            self.strikes[cid] = n
            self.total_strikes += 1
            struck.append(cid)
            if n >= self.max_strikes:
                self.ejected.add(cid)
                self._until.pop(cid, None)
            else:
                # benched for the NEXT `backoff` rounds; re-admitted at
                # rnd + backoff + 1
                self._until[cid] = int(rnd) + self.backoff + 1
        return struck

    # ------------------------------------------------------------- queries

    def blocked(self, rnd: int) -> Set[int]:
        """Clients that must not participate at round ``rnd``: the
        permanently ejected plus everyone still inside a backoff."""
        return self.ejected | {c for c, until in self._until.items()
                               if until > int(rnd)}

    def quarantined(self, rnd: int) -> int:
        """Currently benched (backoff running), NOT counting ejections."""
        return sum(1 for until in self._until.values() if until > int(rnd))

    def ids_digest(self, rnd: int) -> Optional[str]:
        """Compact stable digest of the blocked set for the telemetry
        stream: '<n>:<sha1[:12] of the sorted id list>' — readable count,
        diffable identity, bounded size at any population scale."""
        ids = sorted(self.blocked(rnd))
        if not ids:
            return None
        h = hashlib.sha1(",".join(map(str, ids)).encode()).hexdigest()[:12]
        return f"{len(ids)}:{h}"

    def snapshot(self, rnd: int) -> Dict[str, Any]:
        """The defense-event fields this ledger owns."""
        return {
            "quarantined": self.quarantined(rnd),
            "ejected": len(self.ejected),
            "quarantine_ids_digest": self.ids_digest(rnd),
        }

    # -------------------------------------------------------- persistence

    def state_dict(self) -> Dict[str, Any]:
        """JSON-serializable full state, carried in checkpoint meta so a
        resumed run keeps its bench/eject decisions — without this, a
        restart silently RE-ADMITS every benched and permanently-ejected
        client until they strike all over again (keys stringified for
        JSON; ``load_state_dict`` restores the int keys)."""
        return {
            "strikes": {str(c): n for c, n in self.strikes.items()},
            "until": {str(c): u for c, u in self._until.items()},
            "ejected": sorted(self.ejected),
            "total_strikes": self.total_strikes,
        }

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self.strikes = {int(c): int(n)
                        for c, n in (d.get("strikes") or {}).items()}
        self._until = {int(c): int(u)
                       for c, u in (d.get("until") or {}).items()}
        self.ejected = {int(c) for c in d.get("ejected") or ()}
        self.total_strikes = int(d.get("total_strikes", 0))
