from commefficient_tpu.core.server import server_update, validate_mode_combo
from commefficient_tpu.core.state import FedState
from commefficient_tpu.core.runtime import FedRuntime

__all__ = ["server_update", "validate_mode_combo", "FedState", "FedRuntime"]
