from commefficient_tpu.core.server import server_update, validate_mode_combo
from commefficient_tpu.core.state import FedState
from commefficient_tpu.core.runtime import FedRuntime
from commefficient_tpu.core.pipeline import (DecodeOverlapRound,
                                             RoundInput, RoundPipeline)
from commefficient_tpu.core.async_agg import (AsyncAggregator,
                                              staleness_weight,
                                              validate_async_combo,
                                              validate_overlap_combo)
from commefficient_tpu.core.preempt import (PreemptGuard, RoundWatchdog,
                                            collect_ledger_state,
                                            restore_ledger_state,
                                            with_retries)

__all__ = ["server_update", "validate_mode_combo", "FedState", "FedRuntime",
           "RoundInput", "RoundPipeline", "DecodeOverlapRound",
           "AsyncAggregator", "staleness_weight",
           "validate_async_combo", "validate_overlap_combo",
           "PreemptGuard", "RoundWatchdog", "with_retries",
           "collect_ledger_state", "restore_ledger_state"]
