"""Server-side update rules for the five federated modes.

Pure-functional re-design of the reference's ``get_server_update`` dispatch
and ``_server_helper_*`` family (CommEfficient/fed_aggregator.py:469-613).
The reference mutates momentum/error buffers in place and pokes per-client
velocity arrays through module globals; here every rule is

    (gradient, Vvelocity, Verror, lr) -> (update, Vvelocity', Verror', mask)

with no side effects, so the whole thing jits and differentiates state
threading explicitly. ``mask`` is the boolean nonzero-support of the update in
*transmitted* space (dense coords, or sketch-table cells), returned so the
runtime can apply the reference's momentum-factor-masking to participating
clients' local velocities (fed_aggregator.py:528-533 — note the reference has
a latent bug there: ``g_participating_clients`` is assigned without ``global``
at fed_aggregator.py:220, so its masking never fires; we implement the
documented intent).

Error-feedback/masking scatters (`Verror[update.nonzero()] = 0`) are expressed
as ``jnp.where`` with the support mask — branch-free, fusable, no scatters.

Legal (mode x error_type x momentum) combinations follow the reference's
runtime asserts (fed_worker.py:221-228, fed_aggregator.py:484-486, 512,
545, 573-576); see ``validate_mode_combo``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from commefficient_tpu.config import FedConfig
from commefficient_tpu.ops import topk
from commefficient_tpu.ops.topk import (local_topk_candidates,
                                        merge_topk_candidates,
                                        topk_with_idx)

# Measured divergence envelopes (round 5). local_topk with LOCAL error
# feedback learns only with the LR cut far below the dense-stable value:
# the committed hard-v2 run at lr 0.1 sat at chance (9.7%), the numpy
# transcription of the reference's own dynamics (scripts/local_topk_sim
# --sweep) shows loss ratios of ~4e5x at lr 0.1 / k/d=0.08 and learning
# only at lr ~0.005-0.01, and the TPU confirmation arms learned at 0.01
# and not 0.1 (runs/README.md "local_topk ... with receipts").
LOCAL_TOPK_EF_STABLE_LR = 0.02
# subtract-EF at high collision load: every GPT-2-scale arm (d/c ~ 176)
# diverged at rounds 7-29, with LATER divergence at LOWER load — a dose
# response (runs/gpt2_conv/README.md) — while d/c ~ 13 (CIFAR flagship)
# is the rule's decisive win. The boundary between those measurements:
SUBTRACT_EF_STABLE_LOAD = 100.0


def check_regime_health(cfg: FedConfig) -> List[str]:
    """Warnings for configurations round 5 MEASURED divergent.

    Unlike ``validate_mode_combo`` (illegal combinations), these configs
    are legal and exist to be studied — but a user reaching one by
    accident deserves the measurement up front, not 24 epochs of chance
    accuracy (VERDICT weak #3). Returns human-readable warnings; the
    caller prints them to stderr, or raises under --strict_regimes.
    Needs cfg.grad_size resolved (the collision load is d/c), so it runs
    at runtime init alongside validate_mode_combo.
    """
    warnings: List[str] = []
    if (cfg.mode == "local_topk" and cfg.error_type == "local"
            and cfg.lr_scale is not None
            and cfg.lr_scale > LOCAL_TOPK_EF_STABLE_LR):
        warnings.append(
            f"mode=local_topk with error_type=local at lr_scale="
            f"{cfg.lr_scale} is in the MEASURED divergent regime: local "
            "error feedback at real compression needs the lr cut to "
            f"~{LOCAL_TOPK_EF_STABLE_LR} or below (hard-v2 at lr 0.1 sat "
            "at chance; the reference's own dynamics, transcribed in "
            "scripts/local_topk_sim.py --sweep, diverge identically — "
            "runs/README.md). Cut --lr_scale, or use error_type=none "
            "(tolerates ~10x higher lr and recovered most of true_topk's "
            "quality at the same compression)")
    if (cfg.mode == "sketch" and cfg.sketch_ef == "subtract"
            and cfg.sketch_server_state != "dense" and cfg.grad_size
            and cfg.grad_size / cfg.num_cols >= SUBTRACT_EF_STABLE_LOAD):
        warnings.append(
            f"--sketch_ef subtract at collision load d/c = "
            f"{cfg.grad_size / cfg.num_cols:.0f} (d={cfg.grad_size}, "
            f"c={cfg.num_cols}) is in the MEASURED divergent regime: "
            "every GPT-2-scale arm at d/c ~ 176 died by round 29, with "
            "a dose response in d/c (runs/gpt2_conv/README.md). Use "
            f"d/c < {SUBTRACT_EF_STABLE_LOAD:.0f} (raise --num_cols), "
            "or DROP --sketch_ef subtract and use --sketch_server_state "
            "dense (its own exact-support EF rule is already leak-free "
            "AND stable at this load; the two flags together are "
            "rejected), or the default --sketch_ef zero")
    return warnings


def validate_regimes(cfg: FedConfig) -> None:
    """Print measured-divergence warnings (stderr — stdout belongs to
    the byte-stable console loggers); raise under --strict_regimes."""
    warnings = check_regime_health(cfg)
    if not warnings:
        return
    if cfg.strict_regimes:
        raise ValueError(
            "--strict_regimes: refusing measured-divergent config:\n  "
            + "\n  ".join(warnings))
    import sys
    for w in warnings:
        print(f"WARNING: {w}", file=sys.stderr)


def validate_defense_combo(cfg: FedConfig, mesh=None,
                           seq_axis=None) -> None:
    """Reject adversary/defense/quarantine configurations that cannot be
    implemented soundly on this topology — the fail-fast companion of
    validate_mode_combo for the robustness subsystem."""
    robust = (cfg.defense != "none" or cfg.adversary != "none"
              or cfg.nonfinite_action != "abort")
    if not robust:
        return
    if seq_axis is not None:
        # inside a seq-sharded round each shard holds only its PARTIAL
        # per-client gradient: per-client norms/finite flags/injections
        # computed per shard would describe partials, not clients (the
        # same reason max_grad_norm is forbidden with a seq axis)
        raise ValueError(
            "--adversary/--defense/--nonfinite_action quarantine are "
            "unsupported with a seq mesh axis: they act on PER-CLIENT "
            "transmitted quantities, and a seq-sharded round only ever "
            "holds per-shard partials of them")
    if cfg.defense == "trim" and mesh is not None:
        raise ValueError(
            "--defense trim needs the per-coordinate cross-client sort, "
            "which requires every client's full transmitted vector on "
            "one device — unavailable on a mesh (the client axis is "
            "sharded). Use --defense normclip on a mesh (its cross-shard "
            "cost is one W-sized norm all-gather), or drop the mesh.")
    if cfg.adversary == "labelflip":
        from commefficient_tpu.config import FED_DATASETS
        n_cls = FED_DATASETS.get(cfg.dataset_name, 0)
        if n_cls < 2:
            raise ValueError(
                f"--adversary labelflip needs a classification dataset "
                f"with >= 2 classes; {cfg.dataset_name!r} has "
                f"{n_cls if n_cls > 0 else 'no fixed class count'} — use "
                "signflip/scale/noise/nan for update-space attacks "
                "instead")


def robust_aggregate(cfg: FedConfig, tx: jax.Array, n_valid: jax.Array,
                     ref_thresh: Optional[jax.Array] = None,
                     axis_name: Optional[str] = None):
    """Robust aggregation of the per-client transmitted quantities
    (``--defense``), traced inside the jitted round's client block.

    ``tx`` is (W, ...) — each client's datum-weighted upload (dense
    gradient x n_c, sketch table x n_c, or fedavg delta x n_c);
    ``n_valid`` its (W,) datum counts. All statistics are over the
    PER-DATUM update ``tx_i / n_i`` so differently-sized clients are
    commensurable. Returns ``(agg, cur_med, stats)`` where ``agg``
    replaces the plain ``tx.sum(axis=0)``, ``cur_med`` is this round's
    median per-datum norm (the rolling-reference feed, normclip only —
    None otherwise) and ``stats`` holds the defense-event scalars.

    - **normclip** (Sun et al. 2019): clip each client's per-datum norm
      to ``ref x defense_clip_mult`` where ``ref`` is the rolling median
      of past rounds' median norms (``ref_thresh``; NaN on the first
      round falls back to THIS round's median — itself robust to a <50%
      adversarial cohort). An l2 clip is a rescaling, so it commutes
      with the linear sketch: clipping the dense gradient then encoding
      equals encoding then scaling the table by the same factor
      (pinned by tests/test_defense.py). On a mesh the per-shard norms
      all-gather over ``axis_name`` (W floats) so every shard clips
      against the same global median.
    - **trim** (Yin et al. 2018): per-coordinate trimmed mean — sort
      each coordinate across clients, drop ``floor(trim_frac * V)`` at
      each extreme (V = clients that carried data this round, NOT the
      slot count W: benched/masked placeholders hold no vote, see the
      in-body comment), average the rest uniformly, and rescale by the
      round's datum total so the caller's ``agg / n_total``
      normalization yields the trimmed mean itself. Single device only
      (validate_defense_combo).
    """
    from jax import lax

    W = tx.shape[0]
    denom = jnp.maximum(n_valid, 1.0)
    denb = denom.reshape((W,) + (1,) * (tx.ndim - 1))
    valid = n_valid > 0

    if cfg.defense == "trim":
        assert axis_name is None, "trim is single-device (validated)"
        # zero-datum slots (quarantine-benched, participation-masked)
        # carry NO vote: counting their 0/1 = 0 placeholder updates as
        # honest clients would silently dilute the trimmed mean toward
        # zero (with 2 live clients in an 8-slot round the defended
        # update would shrink 4x). Validity is PER-SLOT, so every
        # coordinate has the same count V of real values — push the
        # invalid slots to +inf, sort, and average ranks [t, V-t) with
        # a traced rank mask (t stays a fraction of the LIVE cohort).
        # A nonfinite upload from a live slot sorts last too: with
        # t >= 1 the trim absorbs it (that IS the defense); at t == 0
        # it poisons the mean and the nan_round abort fires as before.
        validb = valid.reshape((W,) + (1,) * (tx.ndim - 1))
        V = valid.sum()
        t = jnp.floor(cfg.defense_trim_frac * V).astype(jnp.int32)
        u = jnp.where(validb, tx / denb, jnp.inf)
        s = jnp.sort(u, axis=0)             # per-coordinate order stats
        rank = jnp.arange(W).reshape((W,) + (1,) * (tx.ndim - 1))
        keep = (rank >= t) & (rank < V - t)
        n_kept = jnp.maximum(V - 2 * t, 1)
        core_mean = jnp.where(keep, s, 0.0).sum(axis=0) / n_kept
        agg = core_mean * n_valid.sum()
        nan = jnp.full((), jnp.nan, jnp.float32)
        stats = {"clip_frac": nan, "clip_thresh": nan, "clipped_mass": nan,
                 "trim_frac": (2.0 * t / jnp.maximum(V, 1)
                               ).astype(jnp.float32)}
        return agg, None, stats

    assert cfg.defense == "normclip", cfg.defense
    flat = tx.reshape(W, -1)
    norms = jnp.sqrt((flat * flat).sum(axis=1)).astype(jnp.float32) / denom
    usable = valid & jnp.isfinite(norms)
    med_in = jnp.where(usable, norms, jnp.nan)
    if axis_name is not None:
        med_in = lax.all_gather(med_in, axis_name, tiled=True)
    cur_med = jnp.nanmedian(med_in).astype(jnp.float32)
    ref = jnp.where(jnp.isnan(ref_thresh), cur_med, ref_thresh)
    thresh = jnp.float32(cfg.defense_clip_mult) * ref
    factors = jnp.minimum(1.0, thresh / jnp.maximum(norms, 1e-12))
    factors = jnp.where(usable, factors, 1.0)
    agg = (tx * factors.reshape((W,) + (1,) * (tx.ndim - 1))).sum(axis=0)
    n_clipped = ((factors < 1.0) & usable).sum().astype(jnp.float32)
    removed_sq = jnp.where(
        usable, ((1.0 - factors) * norms * denom) ** 2, 0.0).sum()
    n_part = usable.sum().astype(jnp.float32)
    if axis_name is not None:
        n_clipped = lax.psum(n_clipped, axis_name)
        removed_sq = lax.psum(removed_sq, axis_name)
        n_part = lax.psum(n_part, axis_name)
    stats = {
        "clip_frac": n_clipped / jnp.maximum(n_part, 1.0),
        "clip_thresh": thresh,
        "clipped_mass": jnp.sqrt(removed_sq).astype(jnp.float32),
        "trim_frac": jnp.full((), jnp.nan, jnp.float32),
    }
    return agg, cur_med, stats


def validate_mode_combo(cfg: FedConfig) -> None:
    """Reject illegal mode/error/momentum combinations up front.

    The reference lets several illegal combos crash deep inside a worker
    process (fed_worker.py:221-228) or, worse, silently not train (sketch
    with error_type=none zero-sketches Verror forever,
    fed_aggregator.py:578-590); we fail fast with an explanation.
    """
    m, e = cfg.mode, cfg.error_type
    if m == "sketch":
        if (cfg.sketch_impl == "rht" and cfg.grad_size
                and cfg.num_rows * cfg.num_cols < cfg.grad_size):
            # measured (tests/test_learning.py sketch-regime study): at
            # r*c < d the SRHT top-k-over-JL-estimates update EXPANDS the
            # accumulated error instead of contracting it and training
            # diverges within tens of rounds — on every topology, with
            # either error-feedback rule. The count-sketch cell-zeroing
            # rule (circ/hash impls) dissipates k/c of the table's error
            # mass per round and is stable; circ is the default. Hard
            # error by default (the repo's fail-fast philosophy);
            # --allow_divergent_rht opts back in (e.g. to reproduce the
            # divergence study) with a stderr warning — stdout stays
            # machine-readable for the bench/driver contract.
            msg = ("sketch_impl=rht with r*c "
                   f"({cfg.num_rows * cfg.num_cols}) < grad_size "
                   f"({cfg.grad_size}) diverges under error feedback in "
                   "practice (measured: tests/test_learning.py); use "
                   "sketch_impl=circ (default) or hash for compressing "
                   "configurations — rht is safe only when r*c >= d")
            if not cfg.allow_divergent_rht:
                raise ValueError(
                    msg + ". Pass --allow_divergent_rht to proceed anyway.")
            import sys
            print(f"WARNING: {msg}", file=sys.stderr)
        if cfg.sketch_ef == "subtract" and (
                cfg.sketch_server_state == "dense"
                or cfg.sketch_impl == "rht"):
            # the dense-preimage server path (forced for rht's dense
            # transform, opt-in via --sketch_server_state dense) keeps
            # momentum/error as exact (d,) pre-images and zeroes them at
            # the update support — it has no table cells, so neither
            # table-space EF rule applies and the requested subtract rule
            # would be SILENTLY ignored (ADVICE.md). An EF study arm run
            # through this path would measure the wrong rule; fail fast.
            which = ("sketch_server_state=dense"
                     if cfg.sketch_server_state == "dense"
                     else "sketch_impl=rht (its dense transform admits no "
                          "table-cell rule)")
            raise ValueError(
                f"--sketch_ef subtract has no effect with {which}: that "
                "server path applies its own error-feedback rule (exact "
                "support zeroing on dense pre-images / the estimate-space "
                "equivalent) and would silently ignore the requested "
                "table-space subtract. Drop --sketch_ef subtract (these "
                "paths are already leak-free), or use sketch_impl=circ/"
                "hash with sketch_server_state=table to study the "
                "subtract rule.")
        if e != "virtual":
            raise ValueError(
                "mode=sketch requires error_type=virtual (FetchSGD). "
                "error_type=none would unsketch an all-zero error table and "
                "never update; error_type=local allocates client error rows "
                "that the reference's own worker forbids for sketch "
                "(fed_worker.py:221-222 — its server-side 'local' branch at "
                "fed_aggregator.py:579-580 is unreachable dead code), and "
                "unmasked client error rows grow without bound")
        if cfg.local_momentum > 0:
            raise ValueError("mode=sketch cannot use local momentum "
                             "(reference assert fed_worker.py:227-228)")
    elif m == "true_topk":
        if e != "virtual":
            raise ValueError("mode=true_topk requires error_type=virtual "
                             "(reference assert fed_aggregator.py:512)")
    elif m == "local_topk":
        if e not in ("local", "none"):
            raise ValueError("mode=local_topk requires error_type local|none "
                             "(reference assert fed_aggregator.py:545)")
    elif m == "fedavg":
        if e != "none" or cfg.local_momentum != 0:
            raise ValueError("fedavg requires error_type=none and "
                             "local_momentum=0 (reference utils.py:225-228)")
    elif m == "uncompressed":
        if e == "local":
            raise ValueError("mode=uncompressed cannot use local error "
                             "(reference assert fed_worker.py:221-222)")


def server_update(
    cfg: FedConfig,
    gradient: jax.Array,
    Vvelocity: jax.Array,
    Verror: jax.Array,
    lr: jax.Array,
    cs=None,
    dp_rng: Optional[jax.Array] = None,
    dense_preimage: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, Optional[jax.Array]]:
    """Dispatch to the mode's update rule (reference fed_aggregator.py:469-481).

    ``gradient`` is the aggregated transmitted quantity, already averaged by
    datum count (reference fed_aggregator.py:332). ``lr`` may be a scalar or a
    per-parameter vector (Fixup param groups, fed_aggregator.py:411-427).
    Returns (weight_update, Vvelocity', Verror', support_mask_or_None).
    """
    rho = cfg.virtual_momentum
    if cfg.mode == "fedavg":
        # reference fed_aggregator.py:483-495: running average of weight
        # deltas; LR was already applied on the client, so update==Vvelocity.
        Vvel = gradient + rho * Vvelocity
        return Vvel, Vvel, Verror, None

    if cfg.mode == "uncompressed":
        # reference fed_aggregator.py:497-509
        Vvel = gradient + rho * Vvelocity
        grad = Vvel
        if cfg.do_dp and cfg.dp_mode == "server":
            noise = cfg.noise_multiplier * jax.random.normal(
                dp_rng, grad.shape, grad.dtype)
            grad = grad + noise
        return grad * lr, Vvel, Verror, None

    if cfg.mode == "true_topk":
        # reference fed_aggregator.py:511-542
        Vvel = gradient + rho * Vvelocity
        Verr = Verror + Vvel
        update = topk(Verr, k=cfg.k, approx=cfg.approx_topk)
        mask = update != 0
        # error feedback + momentum factor masking at the update support
        Verr = jnp.where(mask, 0.0, Verr)
        Vvel = jnp.where(mask, 0.0, Vvel)
        if cfg.error_decay < 1.0:
            Verr = cfg.error_decay * Verr
        return update * lr, Vvel, Verr, mask

    if cfg.mode == "local_topk":
        # reference fed_aggregator.py:544-566: momentum accumulates onto the
        # already-sparse summed worker top-k; no virtual error, no masking.
        Vvel = gradient + rho * Vvelocity
        return Vvel * lr, Vvel, Verror, None

    if cfg.mode == "sketch":
        # FetchSGD core, reference fed_aggregator.py:568-613. All state lives
        # in (r, c) sketch-table space; tables are linear so the psum'd
        # worker tables equal the sketch of the summed gradient.
        assert cs is not None
        if dense_preimage:
            # Single-device SRHT fast path (runtime._dense_preimage):
            # momentum/error live as dense (d,) pre-images; ``gradient``
            # arrives dense (deferred encode skipped entirely), and ONE
            # enc+dec round-trip of the error injects the sketch noise —
            # that round-trip is exactly what the server "sees" through the
            # compressed channel. Because the pre-images are exact, the
            # reference's error feedback and momentum factor masking
            # ("zero Verror/Vvelocity where the update is nonzero",
            # fed_aggregator.py:596-611) apply EXACTLY at the support — the
            # structure of the true_topk rule with the sketch round-trip
            # inserted before the top-k. Reduces to true_topk bit-for-bit in
            # the lossless limit.
            Vvel = gradient + rho * Vvelocity
            Verr = Verror + Vvel
            ests = cs.decode(cs.encode(Verr))
            update, upd_idx = topk_with_idx(ests, k=cfg.k,
                                            approx=cfg.approx_topk)
            Verr = Verr.at[upd_idx].set(0.0)           # error feedback
            Vvel = Vvel.at[upd_idx].set(0.0)           # momentum mask
            if cfg.error_decay < 1.0:
                Verr = cfg.error_decay * Verr
            return update * lr, Vvel, Verr, None
        Vvel = gradient + rho * Vvelocity
        Verr = Verror + Vvel  # virtual error (the only legal type, see above)
        if getattr(cs, "dense_transform", False):
            # SRHT sketch (ops/rht.py): the transform of a k-sparse update is
            # dense, so "zero the occupied cells" (reference
            # fed_aggregator.py:596-611) would wipe the whole table. The
            # equivalent rule in estimate space: subtract the sketch of the
            # quantity the reference zeroes — the update itself for Verror,
            # and the velocity's estimated values at the update support for
            # Vvelocity (momentum factor masking). In the lossless limit
            # (c >= d', exact decode) this is bit-for-bit the reference rule.
            ests_err, ests_vel = cs.decode(jnp.stack([Verr, Vvel]))
            update, upd_idx = topk_with_idx(ests_err, k=cfg.k,
                                            approx=cfg.approx_topk)
            vel_at_support = jnp.zeros_like(ests_vel).at[upd_idx].set(
                ests_vel[upd_idx])
            enc_upd, enc_vel = cs.encode(jnp.stack([update, vel_at_support]))
            Verr = Verr - enc_upd
            Vvel = Vvel - enc_vel
            if cfg.error_decay < 1.0:
                Verr = cfg.error_decay * Verr
            return update * lr, Vvel, Verr, None
        update, upd_idx = cs.unsketch_with_idx(
            Verr, k=cfg.k, approx=cfg.approx_topk)
        # re-sketch the update to find which table cells it occupies
        # (reference fed_aggregator.py:593-595) — the update is k-sparse, so
        # the sparse encode is exact at O(k·r) instead of O(d·r)
        sketched_update = cs.encode_at(update, upd_idx)
        if cfg.sketch_ef == "subtract":
            # Subtractive error feedback (TPU-native extension, see
            # config.py sketch_ef): remove exactly the extracted estimates
            # instead of zeroing whole cells — colliding coordinates keep
            # their accumulated error. Momentum factor masking becomes
            # "subtract the velocity's estimated values at the support"
            # (the same transformation the reference's zeroing applies to
            # the cells, restricted to the extracted mass). Lossless limit
            # (c >= d, no collisions): bit-for-bit the zero rule.
            Vvel = Vvel - cs.encode_vals_at(cs.decode_at(Vvel, upd_idx),
                                            upd_idx)
            Verr = Verr - sketched_update
            mask = None
        else:
            mask = sketched_update != 0
            Vvel = jnp.where(mask, 0.0, Vvel)
            Verr = jnp.where(mask, 0.0, Verr)
        if cfg.error_decay < 1.0:
            Verr = cfg.error_decay * Verr
        return update * lr, Vvel, Verr, mask

    raise ValueError(f"unknown mode {cfg.mode}")


def sharded_sketch_server_update(
    cfg: FedConfig,
    agg_shard: jax.Array,
    Vvel_shard: jax.Array,
    Verr_shard: jax.Array,
    lr: jax.Array,
    cs,
    *,
    axis: str,
    n_shards: int,
    d_pad: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The sketch-mode server tail, SHARDED — traced inside a
    ``shard_map`` over ``axis`` (core/runtime.py wraps it; the
    replicated twin is ``server_update``'s table branch, and the
    sharded==replicated round-parity gate in ``dryrun_multichip`` pins
    the two to the same numerics).

    Per-shard view (device i of n): ``agg_shard``/``Vvel_shard``/
    ``Verr_shard`` are (r, c/n) COLUMN shards of the datum-normalized
    aggregate table and the momentum/EF state (the aggregate arrives
    reduce-scattered — the client block's ``psum_scatter`` replaced the
    replicated table psum). The tail:

    1. momentum + virtual error, elementwise on the shards (table-space
       linearity: column shards update independently);
    2. ONE small (r, c)-sized all-gather of the error table (stacked
       with the velocity table under the subtract-EF rule, which also
       needs velocity estimates at the winners) — the table is the
       compressed payload, gathering it is cheap by design;
    3. shard-local range decode: device i decodes ONLY global
       coordinates [i*d_pad/n, (i+1)*d_pad/n) (``cs.decode_range``;
       coordinates >= d decode to exactly 0) — the dense (d,) estimate
       vector NEVER materializes on any device, per-device temp drops
       from O(d) to O(d/n);
    4. local top-k candidates + an (n, k_loc)-sized candidate
       all-gather + order-stable merge = the global top-k
       (ops/topk.local_topk_candidates / merge_topk_candidates —
       bitwise the unsharded selection, ties included);
    5. error feedback re-encoded from the k sparse winners
       (``encode_vals_at``, O(k*r) — every shard computes the tiny full
       update table and keeps its column slice), zero-rule cell masking
       or subtract-rule estimate subtraction exactly as the replicated
       branch;
    6. the update leaves as the device's dense (d_pad/n,) coordinate
       shard — matching ``ps_weights``'s sharding, so the weight apply
       runs fully sharded with no further collective.

    ``lr`` is a replicated scalar or the device's (d_pad/n,) shard of
    the per-parameter LR vector. Returns ``(update_shard, Vvel_shard',
    Verr_shard')``.
    """
    from jax import lax

    rho = cfg.virtual_momentum
    Vvel = agg_shard + rho * Vvel_shard
    Verr = Verr_shard + Vvel

    if cfg.sketch_ef == "subtract":
        full = lax.all_gather(jnp.stack([Verr, Vvel]), axis, axis=2,
                              tiled=True)
        Verr_full, Vvel_full = full[0], full[1]
    else:
        Verr_full = lax.all_gather(Verr, axis, axis=1, tiled=True)
        Vvel_full = None

    i = lax.axis_index(axis)
    blk = d_pad // n_shards
    start = i * blk
    ests = cs.decode_range(Verr_full, start, blk)
    loc_vals, loc_idx = local_topk_candidates(ests, cfg.k, start,
                                              approx=cfg.approx_topk)
    cand_v = lax.all_gather(loc_vals, axis)        # (n, k_loc) — the
    cand_i = lax.all_gather(loc_idx, axis)         # ~n*k*8-byte payload
    win_vals, win_idx = merge_topk_candidates(cand_v, cand_i, cfg.k)

    # dense update SHARD: scatter the winners that land in my range
    # (top-k indices are distinct, so set() is sound; out-of-range
    # winners drop)
    rel = win_idx - start
    in_range = (rel >= 0) & (rel < blk)
    update = jnp.zeros((blk,), jnp.float32).at[
        jnp.where(in_range, rel, blk)].set(
            jnp.where(in_range, win_vals, 0.0), mode="drop")

    # error feedback from the k-sparse winners: the same re-encode the
    # replicated branch does (encode_at(update, idx) ==
    # encode_vals_at(vals, idx) by construction)
    c_loc = Verr.shape[1]
    sk_upd = cs.encode_vals_at(win_vals, win_idx)
    sk_upd_sh = lax.dynamic_slice_in_dim(sk_upd, i * c_loc, c_loc, axis=1)
    if cfg.sketch_ef == "subtract":
        vel_ests = cs.decode_at(Vvel_full, win_idx)
        sk_vel = cs.encode_vals_at(vel_ests, win_idx)
        Vvel = Vvel - lax.dynamic_slice_in_dim(sk_vel, i * c_loc, c_loc,
                                               axis=1)
        Verr = Verr - sk_upd_sh
    else:
        mask = sk_upd_sh != 0
        Vvel = jnp.where(mask, 0.0, Vvel)
        Verr = jnp.where(mask, 0.0, Verr)
    if cfg.error_decay < 1.0:
        Verr = cfg.error_decay * Verr
    return update * lr, Vvel, Verr
