"""Preemption-safe rounds: the host-side fault-tolerance layer.

Production TPU fleets preempt: maintenance events deliver SIGTERM with
a grace window, hosts die mid-round, and collectives hang silently.
This module owns the three host mechanisms the shared driver loop
(cv_train.train) wires in:

- :class:`PreemptGuard` — an installable SIGTERM/SIGINT handler. The
  FIRST signal only sets a flag: the round loop notices it at the next
  safe point and drains within the ``--preempt_grace`` budget (finish
  the in-flight round, close the RoundPipeline, flush the
  AsyncAggregator through the existing epoch-flush path, write an
  out-of-cadence ``preempt``-tagged checkpoint with round-granular
  meta, fsync telemetry behind a final `fault` event, exit 0). A
  SECOND signal force-exits immediately — the operator's escape hatch
  when the drain itself is wedged.

- :class:`RoundWatchdog` — a host thread that arms a deadline around
  each round's dispatch+sync. The deadline derives from the rolling
  MEDIAN round time with the health.py MAD envelope (a constant-time
  workload cannot false-fire on scheduler jitter; the multiplier is
  ``--watchdog_mult``). On expiry it calls back ONCE per round — the
  driver fires a critical ``round_stall`` alert through the
  AnomalyMonitor and records an events-only flight-recorder bundle
  (fetching device state is exactly the operation that may be hung).

- :func:`with_retries` — bounded exponential-backoff retry for the
  retryable host-side phases (device_put / gather dispatch): a
  transient transfer failure gets ``attempts`` chances before the
  round is declared dead and the exception propagates to the driver's
  existing abort paths.

Everything here is host-only and dependency-free beyond the standard
library: no jitted code changes, no HLO difference with the layer off
(the guard and watchdog are objects the driver simply does not build).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from commefficient_tpu.telemetry.health import robust_z

# signals a preemption can arrive on (SIGKILL is uncatchable by design)
PREEMPT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptGuard:
    """First signal: request a graceful drain. Second signal: force-exit.

    Installs only from the MAIN thread (CPython restricts
    ``signal.signal`` to it); elsewhere the guard stays inert —
    ``requested`` is simply never set, which degrades to today's
    behavior (the default handler kills the process).
    """

    def __init__(self, grace_s: float = 30.0, *, _exit=os._exit):
        if grace_s <= 0:
            raise ValueError(f"grace_s must be > 0, got {grace_s}")
        self.grace_s = float(grace_s)
        self.requested = False
        self.signal_name: Optional[str] = None
        self.t_signal: Optional[float] = None
        self.installed = False
        self._old: Dict[int, Any] = {}
        self._exit = _exit

    def install(self) -> "PreemptGuard":
        if threading.current_thread() is not threading.main_thread():
            return self          # inert off the main thread (see class doc)
        for sig in PREEMPT_SIGNALS:
            try:
                self._old[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):  # pragma: no cover - exotic host
                continue
        self.installed = bool(self._old)
        return self

    def uninstall(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._old = {}
        self.installed = False

    def grace_used_s(self) -> Optional[float]:
        if self.t_signal is None:
            return None
        return time.monotonic() - self.t_signal

    def request(self, signame: str = "manual") -> None:
        """Programmatic preemption request (tests; also what the signal
        handler does)."""
        self.requested = True
        if self.t_signal is None:
            self.t_signal = time.monotonic()
            self.signal_name = signame

    def force_exit_after(self, delay_s: float) -> threading.Timer:
        """Arm the grace ENFORCEMENT: a daemon timer that force-exits
        the process if the drain itself wedges past the remaining
        budget (a checkpoint save blocked on a hung device, a flush
        stuck in a dead collective — the exact states a preemption
        tends to arrive in). The drain cancels it on success; on expiry
        the process exits 1 — a drain that overran its grace did NOT
        complete, and the fleet's hard kill was coming anyway."""
        def _expire():
            sys.stderr.write(
                f"PREEMPT: drain exceeded the {self.grace_s:.0f}s grace "
                "budget — force exit (resume falls back to the last "
                "durable checkpoint)\n")
            sys.stderr.flush()
            self._exit(1)

        t = threading.Timer(max(float(delay_s), 0.0), _expire)
        t.daemon = True
        t.start()
        return t

    def _handle(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        if self.requested:
            # the drain is already running (or wedged): force out NOW,
            # skipping every finally — the operator asked twice
            sys.stderr.write(
                f"PREEMPT: second signal ({name}) — force exit\n")
            sys.stderr.flush()
            self._exit(128 + int(signum))
            return               # only reachable with a stubbed _exit
        sys.stderr.write(
            f"PREEMPT: {name} received — draining within "
            f"{self.grace_s:.0f}s grace (signal again to force exit)\n")
        sys.stderr.flush()
        self.request(name)

    def __enter__(self) -> "PreemptGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def stall_deadline_s(history, mult: float, *, floor_s: float = 2.0,
                     z: float = 6.0) -> Optional[float]:
    """Deadline for "this round has hung": ``mult x median + z x MAD``
    over the rolling round-time history, with the MAD floored exactly
    like the health.py rules (2% of the median relatively, plus an
    absolute 50 ms so micro-rounds cannot arm a zero-width envelope),
    and the whole deadline floored at ``floor_s``. None until the
    history has enough points to be meaningful (min 4)."""
    hist = [float(h) for h in history]
    if len(hist) < 4:
        return None
    stats = robust_z(0.0, hist, mad_floor_abs=0.05)
    return max(mult * stats["median"] + z * stats["mad"], floor_s)


class RoundWatchdog:
    """Host watchdog thread deadlining each round's dispatch+sync.

    Driver contract::

        wd = RoundWatchdog(on_stall, mult=cfg.watchdog_mult)
        for each round:
            wd.arm(global_round)
            ... dispatch + sync ...
            wd.disarm()          # feeds the measured duration
        wd.close()

    ``on_stall(round, elapsed_s, deadline_s)`` runs on the watchdog
    thread, at most once per armed round; the round itself is never
    interrupted — a stall alert is evidence, the kill decision belongs
    to the operator (or the preemption layer).
    """

    def __init__(self, on_stall: Callable[[int, float, float], None],
                 mult: float = 10.0, *, window: int = 32,
                 floor_s: float = 2.0, poll_s: float = 0.05):
        if mult < 1:
            raise ValueError(f"watchdog mult must be >= 1, got {mult}")
        self.on_stall = on_stall
        self.mult = float(mult)
        self.floor_s = float(floor_s)
        self.history: deque = deque(maxlen=int(window))
        self.stalls = 0
        self._poll_s = float(poll_s)
        self._cond = threading.Condition()
        self._armed: Optional[tuple] = None   # (round, t0, deadline)
        self._fired_round: Optional[int] = None
        self._closing = False
        self._thread = threading.Thread(target=self._worker,
                                        name="round-watchdog", daemon=True)
        self._thread.start()

    def deadline_s(self) -> Optional[float]:
        return stall_deadline_s(self.history, self.mult,
                                floor_s=self.floor_s)

    def arm(self, rnd: int) -> None:
        deadline = self.deadline_s()
        with self._cond:
            self._armed = (int(rnd), time.monotonic(), deadline)
            self._cond.notify_all()

    def disarm(self, observe: bool = True) -> None:
        """``observe=False`` clears the deadline WITHOUT feeding the
        duration into the rolling history. The driver passes False for
        rounds that never synced the device (off the record cadence,
        jax's async dispatch returns in milliseconds): mixing those
        dispatch-only durations with fully-synced round times would
        make the median bimodal-fast and the deadline collapse onto
        the floor — firing round_stall on the first HEALTHY synced
        round that waits out the queued device work."""
        with self._cond:
            if self._armed is None:
                return
            rnd, t0, _ = self._armed
            if observe:
                self.history.append(time.monotonic() - t0)
            self._armed = None
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def _worker(self) -> None:
        while True:
            with self._cond:
                if self._closing:
                    return
                armed = self._armed
                if armed is None or armed[2] is None \
                        or self._fired_round == armed[0]:
                    self._cond.wait(timeout=self._poll_s)
                    continue
                rnd, t0, deadline = armed
                now = time.monotonic()
                if now - t0 < deadline:
                    self._cond.wait(timeout=min(
                        deadline - (now - t0), self._poll_s * 4))
                    continue
                self._fired_round = rnd
                self.stalls += 1
                elapsed = now - t0
            try:
                self.on_stall(rnd, elapsed, deadline)
            except Exception as e:  # noqa: BLE001 — observability only
                print(f"WARNING: watchdog stall callback failed ({e})",
                      file=sys.stderr)


def with_retries(fn: Callable[[], Any], *, attempts: int = 3,
                 base_s: float = 0.1, max_s: float = 2.0,
                 desc: str = "host phase",
                 on_retry: Optional[Callable[[int, Exception], None]]
                 = None) -> Any:
    """Bounded exponential-backoff retry for retryable HOST-side phases
    (device_put, gather dispatch). The final failure propagates — after
    ``attempts`` tries the round is declared dead and the driver's
    existing abort paths own what happens next."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delay = float(base_s)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — re-raised on exhaustion
            if attempt >= attempts:
                raise
            print(f"WARNING: {desc} failed (attempt {attempt}/"
                  f"{attempts}: {e}); retrying in {delay:.2f}s",
                  file=sys.stderr)
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(delay)
            delay = min(delay * 2, float(max_s))


# ------------------------------------------------------- ledger persistence

# hard cap on the serialized participation-ledger sidecar (it rides the
# checkpoint's meta.json, which is read whole at every resume). The
# sketch ledger's state is ~3.5 MiB at ANY population; only the exact
# ledger can grow past this — at roughly 2x10^5 seen clients — and the
# guard names the remedy instead of silently bloating every checkpoint.
# Env-overridable for deliberately exact large-universe runs.
LEDGER_SIDECAR_MAX_BYTES = int(os.environ.get(
    "COMMEFF_LEDGER_SIDECAR_MAX_BYTES", 8 * 1024 * 1024))


def collect_ledger_state(qledger=None, participation=None, monitor=None,
                         telemetry=None) -> Dict[str, Any]:
    """The host-ledger sidecar a round-granular checkpoint carries:
    quarantine strikes/benches/ejections, participation counts,
    anomaly-monitor rolling histories, and the telemetry ring vintage
    (how far the flight-recorder ring had advanced — a resumed bundle
    reader can tell a pre-restart event from a post-restart one). All
    JSON-serializable; everything restores via
    :func:`restore_ledger_state`.

    Fails loudly (ValueError) when the participation ledger's state
    exceeds :data:`LEDGER_SIDECAR_MAX_BYTES` — the exact ledger at
    population scale. The error names ``--population_sketch on`` (the
    bounded-memory backing, telemetry/population.py) as the remedy."""
    out: Dict[str, Any] = {}
    if qledger is not None:
        out["quarantine"] = qledger.state_dict()
    if participation is not None:
        part = participation.state_dict()
        nbytes = len(json.dumps(part).encode())
        if nbytes > LEDGER_SIDECAR_MAX_BYTES:
            raise ValueError(
                f"participation-ledger checkpoint sidecar is "
                f"{nbytes / 2**20:.1f} MiB (> "
                f"{LEDGER_SIDECAR_MAX_BYTES / 2**20:.1f} MiB cap): the "
                f"exact per-client ledger does not scale to this "
                f"universe ({getattr(participation, 'num_clients', '?')} "
                f"registered clients). Pass --population_sketch on (or "
                f"auto) for the bounded-memory sketch ledger, or raise "
                f"COMMEFF_LEDGER_SIDECAR_MAX_BYTES to keep exact state.")
        out["participation"] = part
    if monitor is not None:
        out["monitor"] = monitor.state_dict()
    if telemetry is not None:
        out["ring"] = {"seq": getattr(telemetry, "_seq", 0),
                       "recent": len(getattr(telemetry, "recent", ()))}
    return out


def restore_ledger_state(ledgers: Optional[Dict[str, Any]], *,
                         qledger=None, participation=None,
                         monitor=None) -> None:
    """Apply a saved ledger sidecar to this run's freshly-built host
    ledgers (each only when both the saved state and the live object
    exist — a run that turned quarantine off simply drops that state)."""
    if not ledgers:
        return
    if qledger is not None and ledgers.get("quarantine"):
        qledger.load_state_dict(ledgers["quarantine"])
    if participation is not None and ledgers.get("participation"):
        participation.load_state_dict(ledgers["participation"])
    if monitor is not None and ledgers.get("monitor"):
        monitor.load_state_dict(ledgers["monitor"])
