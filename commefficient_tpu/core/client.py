"""Client-side computation: microbatched gradients, local compression state,
and the FedAvg local-SGD loop.

Re-designs CommEfficient/fed_worker.py (process_batch / local_step /
forward_grad / the fedavg branch of worker_loop) as pure functions over a
*static-shape* per-client batch. The reference runs a Python loop over
variable-size client batches inside worker processes; here every client batch
is padded to a fixed shape with a validity mask, microbatching is a
``lax.scan``, and the whole per-client step is ``vmap``-ed (or shard_map-ed)
over the round's client axis by the runtime.

Loss-function contract
----------------------
``loss_fn(params_pytree, batch_pytree, mask) -> (mean_loss, metrics_tuple)``
where every leaf of ``batch_pytree`` has a leading batch axis, ``mask`` is a
float/bool validity vector over that axis, and ``mean_loss``/metrics are means
over *valid* items. (The reference's ``compute_loss_train`` returns
``(loss, *metrics)``, cv_train.py:67-83.)
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from commefficient_tpu.config import FedConfig
from commefficient_tpu.ops import clip_by_l2_norm, topk


class ClientOut(NamedTuple):
    transmit: jax.Array                # transmitted-space quantity, x n_c
    velocity: Optional[jax.Array]      # updated local velocity row (or None)
    error: Optional[jax.Array]         # updated local error row (or None)
    results: Tuple[jax.Array, ...]     # (mean_loss, *metrics) over the batch
    n_valid: jax.Array                 # () number of valid datums processed
    # per-client population stats (telemetry/clients.py CLIENT_GRAD_KEYS
    # -> scalar), threaded only when the runtime's telemetry gating asks
    # for them (FedRuntime._client_stats) — None otherwise so the arrays
    # are compiled out entirely under --no_telemetry/--no_client_stats
    stats: Optional[dict] = None


# fold constant decorrelating the noise-attack draw from every other
# consumer of the per-client round key (DP noise uses the key directly)
_ADV_FOLD = 0xAD5E


def flip_labels(batch: dict, adv: jax.Array, num_classes: int,
                key: str = "target") -> dict:
    """Label-flipping injection (data space): adversarial clients train
    on ``(C-1) - y`` — the standard flip of the label-poisoning
    literature. ``adv`` is the round's (W,) per-slot adversary mask;
    applied on the full (W, B, ...) batch BEFORE the client compute, so
    it works identically under the vmap, fused and fedavg paths."""
    if key not in batch:
        raise ValueError(
            f"--adversary labelflip needs a {key!r} batch leaf (integer "
            f"class labels); this batch has {sorted(batch)} — label "
            "flipping is only defined for classification datasets")
    t = batch[key]
    advb = adv.reshape((-1,) + (1,) * (t.ndim - 1))
    return {**batch, key: jnp.where(advb, (num_classes - 1) - t, t)}


def inject_adversary(cfg: FedConfig, tx: jax.Array, adv: jax.Array,
                     rngs: jax.Array,
                     n_valid: Optional[jax.Array] = None) -> jax.Array:
    """Update-space adversarial injection, applied to the per-client
    transmitted quantities ``tx`` (W, ...) — dense gradients, sketch
    tables or fedavg weight deltas alike (every kind below commutes with
    the datum weighting already folded into ``tx``):

    - signflip: upload x -1 (gradient-ascent poisoning);
    - scale:    upload x adversary_scale (the boosted / model-replacement
                attack);
    - noise:    upload + adversary_scale * N(0, I) in transmitted space,
                drawn per client from its round key (deterministic);
    - nan:      upload all-NaN (the broken-client case
                --nonfinite_action exists to survive).

    A slot with no valid datums (``n_valid == 0``) uploads NOTHING — a
    masked-out client (scenario participation, quarantine bench) has no
    upload to corrupt, so injecting into its zero placeholder would
    fabricate strikes for a client that never participated.
    """
    kind = cfg.adversary
    if kind in ("none", "labelflip"):
        return tx
    if n_valid is not None:
        adv = adv & (n_valid > 0)
    advb = adv.reshape((-1,) + (1,) * (tx.ndim - 1))
    if kind == "signflip":
        return jnp.where(advb, -tx, tx)
    if kind == "scale":
        return jnp.where(advb, cfg.adversary_scale * tx, tx)
    if kind == "noise":
        noise = jax.vmap(
            lambda r: jax.random.normal(jax.random.fold_in(r, _ADV_FOLD),
                                        tx.shape[1:], tx.dtype))(rngs)
        return jnp.where(advb, tx + cfg.adversary_scale * noise, tx)
    if kind == "nan":
        return jnp.where(advb, jnp.full_like(tx, jnp.nan), tx)
    raise ValueError(f"unknown adversary kind {kind!r}")


def quarantine_zero(tx: jax.Array, n_valid: jax.Array,
                    results: Tuple[jax.Array, ...]
                    ) -> Tuple[jax.Array, jax.Array,
                               Tuple[jax.Array, ...], jax.Array]:
    """Per-client nonfinite containment (``--nonfinite_action
    quarantine``): a client whose transmitted quantity OR loss went
    nonfinite is zeroed out of the round — its upload, its datum count
    (so the aggregate normalization excludes it) and its metric
    contributions (so the epoch accumulators stay finite). Returns
    ``(tx', n_valid', results', finite)`` with ``finite`` the (W,) bool
    flags the host-side QuarantineLedger consumes."""
    flat = tx.reshape(tx.shape[0], -1)
    fin = jnp.isfinite(flat).all(axis=1) & jnp.isfinite(results[0])
    finb = fin.reshape((-1,) + (1,) * (tx.ndim - 1))
    tx = jnp.where(finb, tx, 0.0)
    n_valid = jnp.where(fin, n_valid, 0.0)
    results = tuple(jnp.where(fin, r, 0.0) for r in results)
    return tx, n_valid, results, fin


def int8_wire_uploads(cfg: FedConfig, tx: jax.Array, step: jax.Array,
                      block: int, slot0=0) -> jax.Array:
    """Simulated int8 wire on PER-CLIENT table uploads (--wire_dtype
    int8, non-deferred encode — the path that keeps per-client tables
    for the table clip): each client's (r, c) table quantizes with
    per-column-block abs-max scales + stochastic rounding and
    dequantizes in f32 before the server sum — the server only ever
    sees what crossed the wire. Draws key off (seed, round, GLOBAL
    slot, cell): ``slot0`` offsets the local slot index by the mesh
    shard's base so shards never share a rounding stream. The residual
    ``tx - tx'`` is ordinary compression noise to the server EF."""
    from commefficient_tpu.ops.wire import wire_round_trip
    W = tx.shape[0]
    slots = jnp.arange(W, dtype=jnp.int32) + slot0
    return jax.vmap(
        lambda t, w: wire_round_trip(t, block, seed=cfg.seed,
                                     round_idx=step, salt=w))(tx, slots)


# coalesce adjacent gradient leaves into at-least-this-many-element
# chunks before the streaming encode: biases/layernorm leaves are tiny,
# and one encode_accum per 768-element leaf would pay the per-range
# block padding (and op count) hundreds of times per microbatch.
# Measured best CPU-ledger packing at 1024 (the encode working set stays
# a few blocks while the chunk count stays O(d / 1024)).
_ENCODE_CHUNK_MIN = 1024
# ... and split anything bigger than this into bounded ranges: one
# encode_accum's working set is ~4 chunk-sized buffers (signs, signed
# values, rolled, padding copy), so an uncapped 2M-element kernel leaf
# would put ~32 MB of encode temporaries next to the cotangents the
# fusion exists to shrink. Measured on the CPU ledger: capping at 64k
# cut the fused client scan's temp ~30% with no measurable wall cost
# (the cap only bounds PEAK residency; total encode work is unchanged).
# The cap SCALES with the sketch's d (see _encode_chunk_max): a fixed
# 64k cap at GPT-2 124M would unroll ~1900 encode_accum calls into the
# scan body — a compile-time explosion — while d/32 keeps the chunk
# count O(32) and the working set at ~d/8, far under the d*4 the
# fusion removes.
_ENCODE_CHUNK_MAX = 65536


def _encode_chunk_max(d: int) -> int:
    return max(_ENCODE_CHUNK_MAX, d // 32)


def encode_grad_tree(cs, table, gtree, scale=None, token=None,
                     min_chunk: int = _ENCODE_CHUNK_MIN,
                     max_chunk: int = 0):
    """Encode a gradient PYTREE into a carry sketch table, leaf range by
    leaf range, without ever concatenating the (d,) dense vector.

    The leaves are walked in ravel order (``jax.flatten_util``'s leaf
    order — the layout every ``unravel`` consumer shares), adjacent
    small leaves are coalesced into >= ``min_chunk``-element contiguous
    chunks, oversized leaves are split into <= ``max_chunk`` ranges (the
    encode working set stays bounded), and each chunk streams through
    ``cs.encode_accum`` at its static global offset. Chunks are encoded
    in REVERSE ravel order — the order the backward PRODUCES cotangents
    (last layer first) — so the table-accumulation chain never forces an
    early layer's not-yet-computed gradient ahead of a ready one, and
    the scheduler may free each cotangent at its encode. (XLA's CPU
    scheduler still keeps most of the tree resident — ~1.9x d*4 measured
    against the theoretical interleave; a scan-structured model that
    owns its backward gets all the way under d*4 via the
    ``streaming_grad`` hook, models/stream_mlp.py.) Exception: when the
    sketch's fused Pallas encode kernel is eligible (TPU, aligned
    shifts — CirculantSketch._use_pallas_encode), the whole-vector route
    is faster than per-chunk rolls, so the tree IS raveled once and
    encoded in one kernel call — one (d,) buffer inside the scan step
    instead of the unfused path's persistent (d,) carry pair.

    Returns ``table + encode(scale * ravel(gtree))`` up to fp addition
    order (sketch linearity; pinned by tests/test_fused_encode.py).
    """
    leaves = jax.tree_util.tree_leaves(gtree)
    if getattr(cs, "_use_pallas_encode", lambda: False)():
        flat = jnp.concatenate([l.reshape(-1) for l in leaves])
        return cs.encode_accum(table, flat, 0, scale=scale, token=token)
    if max_chunk <= 0:
        max_chunk = _encode_chunk_max(int(getattr(cs, "d", 0)))
    chunks = []          # (static start, [flat leaf pieces])
    cur, cur_n, cur_start, off = [], 0, 0, 0
    for leaf in leaves:
        flat = leaf.reshape(-1)
        n, pos = int(flat.size), 0
        while n - pos > 0:
            if not cur:
                cur_start = off + pos
            take = min(n - pos, max_chunk - cur_n)
            cur.append(flat[pos:pos + take]
                       if (pos or take < n) else flat)
            cur_n += take
            pos += take
            if cur_n >= max_chunk:
                chunks.append((cur_start, cur))
                cur, cur_n = [], 0
        off += n
        if cur_n >= min_chunk:
            chunks.append((cur_start, cur))
            cur, cur_n = [], 0
    if cur:
        chunks.append((cur_start, cur))
    for start, pieces in reversed(chunks):
        vals = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
        table = cs.encode_accum(table, vals, start, scale=scale,
                                token=token)
    return table


def fused_encode_blockers(cfg: FedConfig, signals: bool = False) -> list:
    """Config-level blockers of the fused sketch encode
    (``--sketch_fused_encode``), mirroring the fail-fast style of
    ``validate_async_combo`` / ``validate_defense_combo``: every entry
    names the dense-space consumer that makes accumulating in table
    space unsound, and what to change. Returns the (possibly empty)
    blocker list; ``FedRuntime`` merges in the topology/impl-dependent
    blockers (dense-preimage server state, the rht transform, defenses
    on the deferred-dense uploads, vmap-path grad stats) and raises
    under ``--sketch_fused_encode on``. ``signals`` is whether the
    per-round signal diagnostics are actually live (telemetry on, no
    async/decode-overlap split) — ``--signals_exact`` only blocks then.
    """
    problems = []
    if cfg.mode != "sketch":
        problems.append(
            f"--mode {cfg.mode} has no sketch encode to fuse")
        return problems
    if cfg.do_dp:
        problems.append(
            "--dp clips and noises the DENSE per-client gradient "
            "(l2_norm_clip + worker noise) before the encode; fusing "
            "would skip the privacy mechanism. Drop --dp, or run the "
            "unfused round")
    if cfg.sketch_dense_clip:
        problems.append(
            "--sketch_dense_clip clips the DENSE worker gradient before "
            "the encode; the fused path never materializes it. Use the "
            "table-Frobenius clip (--max_grad_norm without "
            "--sketch_dense_clip), which stays available fused")
    if cfg.signals_exact and signals:
        problems.append(
            "--signals_exact threads a dense shadow EF accumulator pair "
            "(and the exact dense-error top-k) through the round — both "
            "need the dense aggregated gradient the fusion removes. "
            "Drop --signals_exact (or --no_signals)")
    return problems


def _num_microbatches(cfg: FedConfig, batch_size: int) -> Tuple[int, int]:
    if cfg.microbatch_size > 0:
        mb = min(batch_size, cfg.microbatch_size)
    else:
        mb = batch_size
    return math.ceil(batch_size / mb), mb


def make_forward_grad(
    cfg: FedConfig,
    loss_fn: Callable,
    unravel: Callable[[jax.Array], Any],
    batch_size: int,
    defer_encode: bool = False,
    with_stats: bool = False,
    fused_encode: bool = False,
):
    """Build the microbatched forward/backward (reference fed_worker.py:249-335).

    Returns ``fwd(params_vec, batch, mask, rng, cs) ->
    (g, results, n_valid, stats)`` where ``g`` is in transmitted space:
    the accumulated sum over microbatches of per-microbatch mean
    gradients (matching the reference's ``loss.backward()``
    accumulation), with decoupled weight decay ``wd/num_workers * w``
    added (reference utils.py:254-259), grad-norm clipping, optional DP
    clip+noise, and mode compression (sketch encode).

    ``with_stats`` (telemetry/clients.py): also return per-client scalar
    diagnostics — the dense gradient norm before any clip
    (``grad_norm_pre``), after all clips and DP noise but before encode
    (``grad_norm_post``), and whether the applicable clip actually bound
    (``clip_frac``, NaN when no clip applies). ``stats`` is None when
    disabled, so the extra reductions are compiled out.

    ``fused_encode`` (sketch mode only; FedRuntime gates soundness):
    the microbatch scan carries the (r, c) Count Sketch TABLE instead of
    the (d,) dense gradient sum — each microbatch's gradient is taken
    against the parameter PYTREE (no ravel concat) and streamed into the
    carry via ``encode_grad_tree`` (sum-of-sketches == sketch-of-sum,
    the FetchSGD linearity), so a per-microbatch gradient lives only
    inside one scan step and the returned ``g`` IS the client's table.
    The weight-decay term encodes separately by the same linearity.
    Escape hatch for scan-structured models: a ``loss_fn`` carrying a
    ``streaming_grad`` attribute — ``streaming_grad(params_vec,
    mb_batch, mb_mask, cs, table, scale=None) -> (table, loss,
    metrics)`` — owns its own backward and streams per-LAYER gradients
    into the table (no whole-model gradient pytree at all; contract
    pinned by tests/test_fused_encode.py). Requires no dense-space
    consumer (dense clip/DP/stats) — the runtime validates; asserted
    here. The table-Frobenius clip stays available (per-table op).
    """
    num_iters, mb = _num_microbatches(cfg, batch_size)
    pad_to = num_iters * mb
    if fused_encode:
        # max_grad_norm WITHOUT --sketch_dense_clip is the table-
        # Frobenius clip — a per-table op the fused path applies to its
        # own carry below, so it stays available (as today)
        assert cfg.mode == "sketch" and not with_stats \
            and not cfg.do_dp and not cfg.sketch_dense_clip, \
            "fused_encode eligibility is the runtime's job (see " \
            "FedRuntime); an ineligible combination reached the client"

    def loss_on_vec(vec, mb_batch, mb_mask):
        loss, metrics = loss_fn(unravel(vec), mb_batch, mb_mask)
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_on_vec, has_aux=True)
    # fused-encode: differentiate w.r.t. the PYTREE. Mathematically the
    # same leaf cotangents (unravel is slice+reshape; its VJP is the
    # concatenation we are eliminating) — but the concat never happens,
    # and neither does its in-scan transpose (one pad-to-(d,)-and-add
    # per leaf, measured 131x d·4 temp on the CPU backend).
    tree_grad_fn = (jax.value_and_grad(loss_fn, has_aux=True)
                    if fused_encode else None)
    stream = (getattr(loss_fn, "streaming_grad", None)
              if fused_encode else None)

    def fwd(params_vec, batch, mask, rng, cs=None):
        # ``cs`` is threaded as a CALL-TIME argument (not a closure): its
        # arrays — at GPT-2 scale the int8 sign table alone is ~670 MB —
        # must be jit inputs, not constants baked into (and shipped with)
        # the serialized HLO
        mask = mask.astype(jnp.float32)
        if pad_to != batch_size:
            pad = pad_to - batch_size
            batch = jax.tree.map(
                lambda t: jnp.pad(t, [(0, pad)] + [(0, 0)] * (t.ndim - 1)),
                batch)
            mask = jnp.pad(mask, (0, pad))
        micro_batches = jax.tree.map(
            lambda t: t.reshape((num_iters, mb) + t.shape[1:]), batch)
        micro_masks = mask.reshape(num_iters, mb)

        params = unravel(params_vec) if fused_encode else None

        def body(carry, inp):
            g_acc, loss_acc, metrics_acc = carry
            mb_batch, mb_mask = inp
            if fused_encode:
                # g_acc is the (r, c) table: the per-microbatch gradient
                # exists only inside this step (as leaf cotangents, or
                # not at all on the streaming path)
                if stream is not None:
                    g_acc, loss, metrics = stream(params_vec, mb_batch,
                                                  mb_mask, cs, g_acc)
                else:
                    (loss, metrics), gtree = tree_grad_fn(
                        params, mb_batch, mb_mask)
                    g_acc = encode_grad_tree(cs, g_acc, gtree, token=loss)
            else:
                (loss, metrics), g = grad_fn(params_vec, mb_batch, mb_mask)
                g_acc = g_acc + g
            w = mb_mask.sum()
            metrics_acc = jax.tree.map(
                lambda a, m: a + m * w, metrics_acc, tuple(metrics))
            return (g_acc, loss_acc + loss * w, metrics_acc), None

        # probe metrics structure without running the model twice: metrics
        # accumulators start at zero scalars shaped like the loss outputs
        metrics_zero = tuple(
            jnp.zeros(()) for _ in range(cfg.num_results_train - 1))
        if fused_encode:
            assert cs is not None, "fused encode requires the runtime's sketch"
            g_init = cs.empty_table()
        else:
            g_init = jnp.zeros_like(params_vec)
        init = (g_init, jnp.zeros(()), metrics_zero)
        (g, loss_sum, metrics_sum), _ = lax.scan(
            body, init, (micro_batches, micro_masks))

        n_valid = mask.sum()
        denom = jnp.maximum(n_valid, 1.0)
        results = (loss_sum / denom,) + tuple(
            m / denom for m in metrics_sum)

        # decoupled weight decay (reference utils.py:254-259). Seq-sharded
        # rounds sum per-shard terms then divide by the shard count in the
        # runtime's aggregation, so no per-shard correction is needed here.
        # Fused-encode: the wd term is linear too, so it encodes straight
        # into the table (whole-vector range — the Pallas route when
        # eligible) instead of forcing a dense g back into existence.
        if cfg.weight_decay != 0:
            if fused_encode:
                g = cs.encode_accum(
                    g, params_vec, 0,
                    scale=cfg.weight_decay / cfg.num_workers,
                    token=loss_sum)
            else:
                g = g + (cfg.weight_decay / cfg.num_workers) * params_vec
        stats = None
        if with_stats:
            # telemetry/clients.py: the clip threshold this client's
            # gradient is measured against — DP takes precedence (its
            # clip runs after, on the already-clipped gradient, and is
            # the binding one for DP runs); NaN when nothing clips
            pre = jnp.sqrt(jnp.vdot(g, g)).astype(jnp.float32)
            if cfg.do_dp:
                thresh = jnp.float32(cfg.l2_norm_clip)
            elif cfg.max_grad_norm is not None and (
                    cfg.mode != "sketch" or cfg.sketch_dense_clip):
                thresh = jnp.float32(cfg.max_grad_norm * num_iters)
            else:
                thresh = jnp.float32(jnp.nan)
            stats = {
                "grad_norm_pre": pre,
                "clip_frac": jnp.where(jnp.isnan(thresh), jnp.nan,
                                       (pre > thresh).astype(jnp.float32)),
            }
        # grad-norm clipping for dense modes (reference fed_worker.py:290-292;
        # threshold scales with the number of accumulation steps). Not
        # available seq-sharded (the runtime forbids it): the clip needs the
        # norm of the SUMMED client gradient, which per-shard norms cannot
        # provide (partials are not orthogonal). --sketch_dense_clip
        # extends the same PRE-encode clip to sketch mode (the reference
        # can only clip the post-encode table, fed_worker.py:318-319 — by
        # sketch linearity the same rescaling at a matched threshold, but
        # with bare instead of x num_iters threshold semantics; measured
        # study in runs/gpt2_conv/README.md).
        if cfg.max_grad_norm is not None and (
                cfg.mode != "sketch" or cfg.sketch_dense_clip):
            g = clip_by_l2_norm(g, cfg.max_grad_norm * num_iters)
        # differential privacy (reference fed_worker.py:304-309)
        if cfg.do_dp:
            g = clip_by_l2_norm(g, cfg.l2_norm_clip)
            if cfg.dp_mode == "worker":
                noise = cfg.noise_multiplier * jnp.sqrt(
                    1.0 * cfg.num_workers) * jax.random.normal(
                        rng, g.shape, g.dtype)
                g = g + noise
        if with_stats:
            # post-clip/post-noise dense norm: what this client actually
            # contributes through the channel (measured BEFORE the
            # sketch encode so the space matches grad_norm_pre)
            stats["grad_norm_post"] = jnp.sqrt(
                jnp.vdot(g, g)).astype(jnp.float32)
        # mode compression (reference fed_worker.py:312-333). When
        # ``defer_encode`` the runtime exploits sketch linearity
        # (sum-of-sketches == sketch-of-sum) to encode ONCE after the
        # cross-client sum instead of once per client — legal whenever no
        # per-client nonlinearity acts on the table (no table clip).
        # Fused-encode: ``g`` already IS this client's table, so only
        # the per-table ops (the Frobenius clip) remain.
        if cfg.mode == "sketch" and fused_encode:
            if cfg.max_grad_norm is not None and not cfg.sketch_dense_clip:
                # reference semantics: clip the TABLE (fed_worker.py:318)
                g = cs.clip(g, cfg.max_grad_norm)
        elif cfg.mode == "sketch" and not defer_encode:
            assert cs is not None, "sketch mode requires the runtime's sketch"
            table = cs.encode(g)
            if cfg.max_grad_norm is not None and not cfg.sketch_dense_clip:
                # reference semantics: clip the TABLE (fed_worker.py:318)
                table = cs.clip(table, cfg.max_grad_norm)
            g = table
        return g, results, n_valid, stats

    return fwd


def make_fused_grad(
    cfg: FedConfig,
    loss_fn: Callable,
    unravel: Callable[[jax.Array], Any],
    batch_size: int,
    fused_encode: bool = False,
):
    """Jointly-computed round gradient: one microbatch scan over ALL of the
    round's clients instead of ``vmap(per-client scan)``.

    The aggregation the server consumes is ``sum_c n_c * g_c`` where
    ``g_c = sum_mb grad(mean loss of mb) + wd-term`` (fed_worker.py:190 +
    fed_aggregator.py:332 weighting). When no per-client nonlinearity
    intervenes (no local momentum/error rows, no per-client clip/DP/table
    op — ``FedRuntime._fused`` checks), that sum is linear in the
    per-microbatch gradients, so it can be accumulated into ONE (d,)
    buffer with each microbatch's gradient weighted by its client's datum
    count. The vmapped path instead materializes a per-client (W, d)
    gradient (2.9 GB at GPT-2 92M x 8 clients) and, inside the backward,
    W separate embedding-gradient accumulators — the profiler measured
    ~67 ms/round of the flagship GPT-2 round in exactly those per-client
    wte-gradient buffers (runs/profile_gpt2/BREAKDOWN.md).

    ``fused_encode`` (sketch mode; FedRuntime gates soundness) goes one
    step further down the same linearity: the scan carry is the (r, c)
    Count Sketch TABLE, each microbatch's gradient pytree streams into
    it via ``encode_grad_tree`` scaled by its client's datum count, and
    the round's ONE (d,) accumulator disappears too — the returned ``g``
    is the round's summed table (sketch-of-weighted-sum). The runtime's
    deferred encode-once then becomes a no-op (the degenerate case).

    Exactness relies on microbatches never straddling clients: requires
    ``batch_size % microbatch == 0`` (checked by the runtime's
    eligibility predicate). Per-client results/n_valid keep their (W,)
    shapes — each microbatch's owning client index rides the scan xs.
    """
    num_iters, mb = _num_microbatches(cfg, batch_size)
    assert num_iters * mb == batch_size, (num_iters, mb, batch_size)
    if fused_encode:
        assert cfg.mode == "sketch" and not cfg.do_dp \
            and not cfg.sketch_dense_clip and cfg.max_grad_norm is None, \
            "fused_encode eligibility is the runtime's job (see FedRuntime)"

    def loss_on_vec(vec, mb_batch, mb_mask):
        return loss_fn(unravel(vec), mb_batch, mb_mask)

    grad_fn = jax.value_and_grad(loss_on_vec, has_aux=True)
    # fused-encode: differentiate w.r.t. the PYTREE (see make_forward_grad
    # — same cotangents, no concat and no in-scan pad-to-(d,) transpose)
    tree_grad_fn = (jax.value_and_grad(loss_fn, has_aux=True)
                    if fused_encode else None)
    stream = (getattr(loss_fn, "streaming_grad", None)
              if fused_encode else None)

    def fused(params_vec, batch, mask, cs=None):
        W = mask.shape[0]
        maskf = mask.astype(jnp.float32)
        n_per_client = maskf.sum(axis=1)                     # (W,)
        flat = jax.tree.map(
            lambda t: t.reshape((W * num_iters, mb) + t.shape[2:]), batch)
        flat_mask = maskf.reshape(W * num_iters, mb)
        n_res = cfg.num_results_train

        client_of_mb = jnp.repeat(jnp.arange(W), num_iters)
        nc_of_mb = jnp.repeat(n_per_client, num_iters)

        params = unravel(params_vec) if fused_encode else None

        def body(carry, inp):
            g_acc, sums = carry
            mb_batch, mb_mask, c, nc = inp
            if fused_encode:
                # g_acc is the round's (r, c) table: the microbatch
                # gradient exists only inside this step, scaled by its
                # client's datum count on the way in (linearity)
                if stream is not None:
                    g_acc, loss, metrics = stream(params_vec, mb_batch,
                                                  mb_mask, cs, g_acc,
                                                  scale=nc)
                else:
                    (loss, metrics), gtree = tree_grad_fn(
                        params, mb_batch, mb_mask)
                    g_acc = encode_grad_tree(cs, g_acc, gtree, scale=nc,
                                             token=loss)
            else:
                (loss, metrics), g = grad_fn(params_vec, mb_batch, mb_mask)
                g_acc = g_acc + g * nc
            w = mb_mask.sum()
            sums = sums.at[:, c].add(
                jnp.stack((loss,) + tuple(metrics)) * w)
            return (g_acc, sums), None

        if fused_encode:
            assert cs is not None, "fused encode requires the runtime's sketch"
            g_init = cs.empty_table()
        else:
            g_init = jnp.zeros_like(params_vec)
        init = (g_init, jnp.zeros((n_res, W)))
        (g, sums), _ = lax.scan(
            body, init, (flat, flat_mask, client_of_mb, nc_of_mb))
        # decoupled weight decay, summed over the round's clients (equal to
        # the per-client term (wd/W)*w scaled by n_c and summed); fused-
        # encode streams it into the table by the same linearity
        if cfg.weight_decay != 0:
            wd_scale = ((cfg.weight_decay / cfg.num_workers)
                        * n_per_client.sum())
            if fused_encode:
                g = cs.encode_accum(g, params_vec, 0, scale=wd_scale,
                                    token=sums[0].sum())
            else:
                g = g + wd_scale * params_vec
        denom = jnp.maximum(n_per_client, 1.0)
        results = tuple(sums[j] / denom for j in range(n_res))
        return g, results, n_per_client

    return fused


def make_client_step(
    cfg: FedConfig,
    loss_fn: Callable,
    unravel: Callable[[jax.Array], Any],
    batch_size: int,
    defer_encode: bool = False,
    with_stats: bool = False,
    fused_encode: bool = False,
):
    """Single-round client step: forward_grad + local momentum / error /
    local-topk pipeline (reference fed_worker.py:184-230).

    Returns ``step(params_vec, batch, mask, velocity, error, rng, cs)
    -> ClientOut``.
    ``velocity``/``error`` are this client's persistent rows (or None when the
    mode doesn't allocate them, reference fed_aggregator.py:105-129).

    ``fused_encode`` (sketch mode — which forbids local momentum/error
    rows, so the post-fwd pipeline below is shape-agnostic): ``g`` comes
    back as this client's (r, c) table and the datum-count weighting /
    quarantine / injection all act on it by sketch linearity.

    Seq-sharded rounds (runtime seq axis): the loss closure itself carries
    the seq semantics (losses.make_gpt2_train_loss seq_axis); this step is
    per-shard linear and the runtime handles the cross-shard sum/scale.
    """
    fwd = make_forward_grad(cfg, loss_fn, unravel, batch_size,
                            defer_encode=defer_encode,
                            with_stats=with_stats,
                            fused_encode=fused_encode)

    def step(params_vec, batch, mask, velocity, error, rng,
             cs=None) -> ClientOut:
        g, results, n_valid, stats = fwd(params_vec, batch, mask, rng, cs)
        # weight by datum count: the server divides by the round's total
        # (reference fed_worker.py:190, fed_aggregator.py:332)
        g = g * n_valid

        new_velocity, new_error = velocity, error
        if cfg.local_momentum > 0:
            new_velocity = cfg.local_momentum * velocity + g
            base = new_velocity
        else:
            base = g

        if cfg.error_type == "local":
            new_error = error + base
            to_transmit = new_error
        else:
            to_transmit = base

        if cfg.mode == "local_topk":
            to_transmit = topk(to_transmit, k=cfg.k, approx=cfg.approx_topk)
            nz = to_transmit != 0
            if new_error is not None:
                new_error = jnp.where(nz, 0.0, new_error)   # error feedback
            if cfg.local_momentum > 0:
                new_velocity = jnp.where(nz, 0.0, new_velocity)  # factor mask

        if stats is not None:
            # update-contribution norm: the transmitted quantity AFTER
            # local momentum / error feedback / local-topk — dense L2,
            # or table Frobenius for the non-deferred sketch encode
            stats["tx_norm"] = jnp.sqrt(
                jnp.vdot(to_transmit, to_transmit)).astype(jnp.float32)
        return ClientOut(to_transmit, new_velocity, new_error, results,
                         n_valid, stats)

    return step


def make_fedavg_client(
    cfg: FedConfig,
    loss_fn: Callable,
    unravel: Callable[[jax.Array], Any],
    batch_size: int,
    with_stats: bool = False,
):
    """FedAvg local-SGD loop (reference fed_worker.py:61-113).

    The client's whole (padded) dataset arrives as one batch; it is split
    into ``fedavg_batch_size`` chunks, trained for ``num_fedavg_epochs``
    epochs of local SGD with per-step decay ``fedavg_lr_decay**step``, and
    the dataset-size-weighted weight delta is transmitted.

    Returns ``step(params_vec, batch, mask, lr, rng) -> ClientOut``
    (fedavg transmits raw weight deltas; no sketch argument).
    """
    if cfg.fedavg_batch_size == -1:
        chunk = batch_size
    else:
        chunk = min(cfg.fedavg_batch_size, batch_size)
    n_chunks = math.ceil(batch_size / chunk)
    pad_to = n_chunks * chunk
    fwd = make_forward_grad(cfg, loss_fn, unravel, chunk)

    def step(params_vec, batch, mask, lr, rng) -> ClientOut:
        mask = mask.astype(jnp.float32)
        n_c = mask.sum()
        if pad_to != batch_size:
            pad = pad_to - batch_size
            batch = jax.tree.map(
                lambda t: jnp.pad(t, [(0, pad)] + [(0, 0)] * (t.ndim - 1)),
                batch)
            mask = jnp.pad(mask, (0, pad))
        chunks = jax.tree.map(
            lambda t: t.reshape((n_chunks, chunk) + t.shape[1:]), batch)
        chunk_masks = mask.reshape(n_chunks, chunk)

        n_steps = n_chunks * cfg.num_fedavg_epochs
        rngs = jax.random.split(rng, n_steps).reshape(
            (cfg.num_fedavg_epochs, n_chunks) + rng.shape)

        def chunk_body(carry, inp):
            w, step_idx, res_acc = carry
            c_batch, c_mask, c_rng = inp
            g, results, n_valid, _ = fwd(w, c_batch, c_mask, c_rng)
            # fully-padded chunks (mask all zero) must be no-ops: no SGD
            # step (g would still carry the weight-decay term), no decay
            # advance, no metric contribution — the reference only ever
            # iterates real minibatches (fed_worker.py:68-77)
            valid = (n_valid > 0).astype(jnp.float32)
            # g is the (possibly multi-microbatch) mean-gradient sum; the
            # reference divides the transmitted sum back by the chunk size
            # before stepping (fed_worker.py:96-100) — our fwd already
            # returns the per-chunk mean accumulation, so apply it directly.
            decay = cfg.fedavg_lr_decay ** step_idx
            w = w - g * (lr * decay * valid)
            res_acc = jax.tree.map(lambda a, r: a + r * n_valid,
                                   res_acc, tuple(results))
            return (w, step_idx + valid, res_acc), None

        def epoch_body(carry, epoch_rngs):
            # inner scan closes over the one resident copy of the chunks
            # (reference's epoch x chunk loops, fed_worker.py:82-101)
            carry, _ = lax.scan(chunk_body, carry,
                                (chunks, chunk_masks, epoch_rngs))
            return carry, None

        res_zero = tuple(jnp.zeros(()) for _ in range(cfg.num_results_train))
        (w_final, _, res_acc), _ = lax.scan(
            epoch_body, (params_vec, 0.0, res_zero), rngs)

        # datum-weighted means over the client's real data
        total = jnp.maximum(n_c * cfg.num_fedavg_epochs, 1.0)
        results = tuple(r / total for r in res_acc)
        # dataset-size weighting (reference fed_worker.py:104-108)
        transmit = (params_vec - w_final) * n_c
        stats = None
        if with_stats:
            # fedavg transmits a weight delta, not a gradient: the
            # per-chunk gradient norms are not the population signal
            # (and straddle the local SGD trajectory), so only the
            # update-contribution norm is meaningful — the rest stay
            # NaN ("not applicable"), never silently zero
            nan = jnp.full((), jnp.nan, jnp.float32)
            stats = {"grad_norm_pre": nan, "grad_norm_post": nan,
                     "clip_frac": nan,
                     "tx_norm": jnp.sqrt(
                         jnp.vdot(transmit, transmit)).astype(jnp.float32)}
        return ClientOut(transmit, None, None, results, n_c, stats)

    return step


def make_val_step(cfg: FedConfig, loss_fn: Callable,
                  unravel: Callable[[jax.Array], Any]):
    """Masked evaluation (reference fed_worker.py:179-181 with
    compute_grad=False): returns (results_tuple, n_valid)."""

    def val(params_vec, batch, mask):
        mask = mask.astype(jnp.float32)
        loss, metrics = loss_fn(unravel(params_vec), batch, mask)
        return (loss,) + tuple(metrics), mask.sum()

    return val


def topk_down_weights(cfg: FedConfig, ps_weights: jax.Array,
                      worker_weights: jax.Array) -> jax.Array:
    """Download-compression emulation (reference fed_worker.py:232-247):
    the client's stale weights advance by the top-k of its lag."""
    diff = ps_weights - worker_weights
    return worker_weights + topk(diff, k=cfg.k, approx=cfg.approx_topk)
