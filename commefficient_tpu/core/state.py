"""FedState: the complete on-device state of a federated run.

The reference scatters this state across a shared-memory host tensor
(``g_ps_weights``, fed_aggregator.py:94-97), optimizer attributes
(``Vvelocity``/``Verror``, fed_aggregator.py:408-409), module globals
(``g_client_velocities``), per-object arrays (``client_errors``,
``client_weights``, fed_aggregator.py:105-129) and host-side download
bookkeeping (fed_aggregator.py:171-194). Here it is one pytree that stays
resident on device across rounds — the reference's per-round host↔device
weight bounce (fed_worker.py:41, fed_aggregator.py:455) disappears.

Byte accounting is re-designed for device residency: instead of a deque of
full past weight vectors (reference fed_aggregator.py:179-194), we keep
``coord_last_update`` — the round index at which each coordinate last
changed — and ``client_last_round``. A client's download cost is then
4 bytes x |{i : coord_last_update[i] >= client_last_round[c]}|, which is
*exact* (the reference's deque clamps staleness at 10/participation and
underestimates), O(d) memory instead of O(d·history), and a pure reduction.

Upload accounting is wire-dtype-exact since schema v9
(``FedConfig.upload_wire_bytes``): the f32 wire keeps the reference's
4 bytes/float, ``--wire_dtype bfloat16`` counts 2 bytes/cell, and
``--wire_dtype int8`` counts 1 byte/cell PLUS the 4-byte f32 scale per
column block — the simulated payload is exactly what the quantized wire
(ops/wire.py) puts on it, scales included.
"""

from __future__ import annotations

from typing import Optional

import jax
from flax import struct


@struct.dataclass
class FedState:
    ps_weights: jax.Array                     # (d,) fp32
    Vvelocity: jax.Array                      # transmitted shape
    Verror: jax.Array                         # transmitted shape
    step: jax.Array                           # () int32, round counter
    rng: jax.Array                            # PRNG key
    # per-client persistent state, allocated only for modes that need it
    # (reference fed_aggregator.py:105-129)
    client_velocities: Optional[jax.Array] = None  # (num_clients, *tx)
    client_errors: Optional[jax.Array] = None      # (num_clients, *tx)
    client_weights: Optional[jax.Array] = None     # (num_clients, d), topk_down
    # byte accounting (see module docstring)
    coord_last_update: Optional[jax.Array] = None  # (d,) int32, init -1
    client_last_round: Optional[jax.Array] = None  # (num_clients,) int32
    # device-side divergence flag: the first round whose weight update went
    # non-finite, or -1. The reference checks the loss on the host every
    # round (cv_train.py:222-224); keeping the flag in device state
    # preserves the fetch-once-per-epoch discipline while still reporting
    # the exact offending round — and lets drivers refuse to checkpoint
    # poisoned state.
    nan_round: Optional[jax.Array] = None          # () int32, init -1
    # --signals_exact dense shadow EF accumulators for table-state sketch
    # (telemetry/signals.py): what an exact-state server would hold, so
    # the heavy-hitter recovery overlap has a dense reference. Allocated
    # only single-device with deferred encode (the only place the dense
    # summed gradient exists); diagnostics-only — never feeds the update.
    # A checkpoint written without them restores None; the drivers
    # (cv_train.setup_checkpointing) re-zero them on resume when the
    # runtime expects a shadow, so the shadow (not the run) restarts
    # from zero instead of the signal silently going dead.
    sig_Vvelocity: Optional[jax.Array] = None      # (d,) fp32
    sig_Verror: Optional[jax.Array] = None         # (d,) fp32
    # async buffered aggregation (core/async_agg.py), allocated only
    # under --async_agg: the staleness-weighted sum of landed-but-
    # uncommitted cohort uploads (transmitted shape — sketch table or
    # dense vector, exactly like Vvelocity) and their RAW datum count
    # (NOT discounted — FedBuff's divide-by-K; weighting the denominator
    # too would cancel the staleness attenuation, see
    # runtime._merge_step). Living in FedState means the buffer checkpoints/restores
    # with everything else; ``step`` counts COMMITS in async mode (the
    # server version), not dispatches. A resumed run must never reuse a
    # non-empty buffer (the epoch replays from its boundary, so the
    # buffered cohorts would be recomputed and double-counted) — the
    # drivers loudly zero it, see async_agg.reconcile_resumed_state.
    async_buffer: Optional[jax.Array] = None       # transmitted shape
    async_buffer_n: Optional[jax.Array] = None     # () fp32
    # --defense normclip rolling reference (core/server.robust_aggregate):
    # a (defense_window,) NaN-initialized ring of past rounds' median
    # per-datum update norms. The clip threshold is
    # nanmedian(ring) x defense_clip_mult — median-of-medians, so one
    # boosted round cannot drag the envelope after it, and NaN slots
    # (rounds not yet seen) are simply ignored. Replicated on a mesh
    # (a window of scalars); checkpoints written before it existed
    # restore None and the driver re-initializes it to NaN — the
    # reference (not the run) restarts cold, see cv_train.
    defense_ref: Optional[jax.Array] = None        # (defense_window,) fp32
