"""Magnitude top-k sparsification and L2 clipping, XLA-native.

Reference behavior: CommEfficient/utils.py:232-252 (`_topk`) selects the k
largest-magnitude entries (by squared value) and returns a dense vector that
is zero elsewhere; supports 1-D vectors and row-wise 2-D. The reference works
around CUDA ``topk`` NaN bugs with zero-initialized output buffers
(utils.py:239-244); under XLA ``lax.top_k`` is deterministic so no workaround
is needed — we instead express the densify step as a scatter, which XLA lowers
efficiently on TPU.

``clip_by_l2_norm`` mirrors CommEfficient/utils.py:305-313 (`clip_grad`) but
as a branch-free `where` so it stays inside ``jit``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def topk_with_idx(vec: jax.Array, k: int, approx: bool = False):
    """Like ``topk`` (1-D) but also returns the (k,) support indices."""
    if approx:
        # TPU-native approximate top-k (Chern et al. bucketed reduction):
        # ~10x faster than exact sort-based top_k on multi-million-element
        # vectors at 0.95 recall — well-suited to top-k *sparsification*,
        # which is itself an approximation (a near-top coordinate surviving
        # one more round in the error accumulator is benign)
        _, idx = lax.approx_max_k(vec * vec, k, recall_target=0.95)
    else:
        _, idx = lax.top_k(vec * vec, k)
    return jnp.zeros_like(vec).at[idx].set(vec[idx]), idx


def local_topk_candidates(vec: jax.Array, k: int, offset=0,
                          approx: bool = False):
    """Per-shard candidate stage of a sharded global top-k.

    ``vec`` is one shard's contiguous slice of the global vector,
    starting at global coordinate ``offset`` (python int or traced
    scalar — e.g. ``axis_index * shard_len`` inside a shard_map).
    Returns the local top ``min(k, len)`` entries as ``(values, global
    indices)``, sorted by descending squared magnitude with ties in
    ascending index order (``lax.top_k`` is stable) — the ordering
    contract ``merge_topk_candidates`` needs to reproduce the unsharded
    selection exactly. ``approx`` uses the TPU bucketed approximate
    top-k per shard (composing two approximations; recovery recall is
    bounded below by the local kernel's target, same rationale as
    ``topk_with_idx``).

    Taking min(k, len) candidates is what makes the merge EXACT: the
    global top-k has at most min(k, len) winners inside any one shard,
    so every global winner is among its shard's candidates.
    """
    k_loc = min(int(k), vec.shape[0])
    if approx:
        _, li = lax.approx_max_k(vec * vec, k_loc, recall_target=0.95)
    else:
        _, li = lax.top_k(vec * vec, k_loc)
    return vec[li], jnp.asarray(offset, jnp.int32) + li.astype(jnp.int32)


def merge_topk_candidates(cand_vals: jax.Array, cand_idx: jax.Array,
                          k: int):
    """Merge per-shard top-k candidates into the global top-k.

    ``cand_vals``/``cand_idx`` are ``(n_shards, k_loc)`` stacks from
    ``local_topk_candidates`` over ``n_shards`` contiguous slices in
    global index order (the shape a per-shard all-gather produces).
    Returns ``(values, indices)`` — the exact sequence
    ``topk_with_idx`` produces on the concatenated vector.

    Order-stability: within a shard, equal-magnitude candidates appear
    in ascending index order (stable local top-k); across shards, shard
    order IS global index order (contiguous slices). So the flattened
    candidate order is consistent with ascending global index among
    equal magnitudes, and ``lax.top_k``'s first-occurrence tie-breaking
    selects the same coordinates in the same order as the unsharded
    top-k — including ties that straddle shard boundaries (pinned by
    tests/test_sharded_server.py). Handles k not divisible by n_shards
    (k_loc = min(k, shard_len), the merge just ranks n*k_loc
    candidates) and k >= shard_len (every shard contributes its whole
    slice and the merge degenerates to the exact unsharded top-k).
    """
    flat_v = cand_vals.reshape(-1)
    flat_i = cand_idx.reshape(-1)
    assert flat_v.shape[0] >= k, (
        f"{cand_vals.shape} candidates cannot cover k={k}: each shard "
        "must contribute min(k, shard_len) candidates")
    _, sel = lax.top_k(flat_v * flat_v, k)
    return flat_v[sel], flat_i[sel]


def _topk_1d(vec: jax.Array, k: int, approx: bool = False) -> jax.Array:
    return topk_with_idx(vec, k, approx)[0]


def topk(vec: jax.Array, k: int, approx: bool = False) -> jax.Array:
    """Dense vector keeping only the k largest-magnitude entries.

    1-D: top-k over the whole vector. 2-D: row-wise top-k (each row keeps its
    own k entries), matching reference utils.py:249-252. ``approx`` selects
    the TPU-optimized approximate kernel (see _topk_1d).
    """
    if vec.ndim == 1:
        return _topk_1d(vec, k, approx)
    if vec.ndim == 2:
        return jax.vmap(lambda row: _topk_1d(row, k, approx))(vec)
    raise ValueError(f"topk supports 1-D/2-D, got shape {vec.shape}")


def median_axis0(x: jax.Array) -> jax.Array:
    """Median over a SMALL leading axis via a min/max comparator network.

    ``jnp.median`` lowers to a sort along the axis, which XLA executes as a
    full variadic sort — >100 ms for (5, 8M) on TPU. A bubble sorting network
    is r(r-1)/2 pairwise min/max ops, each a fused elementwise kernel, so the
    whole median streams at HBM bandwidth (~1-2 ms at the same size). Matches
    numpy median semantics (mean of the two middles for even r).
    """
    r = x.shape[0]
    if r == 1:
        return x[0]
    rows = [x[i] for i in range(r)]
    for i in range(r):
        for j in range(r - 1 - i):
            lo = jnp.minimum(rows[j], rows[j + 1])
            hi = jnp.maximum(rows[j], rows[j + 1])
            rows[j], rows[j + 1] = lo, hi
    if r % 2:
        return rows[r // 2]
    return 0.5 * (rows[r // 2 - 1] + rows[r // 2])


def clip_by_l2_norm(record: jax.Array, clip: float) -> jax.Array:
    """Scale ``record`` down to L2 norm ``clip`` if it exceeds it.

    Matches reference ``clip_grad`` (utils.py:305-313): dense vectors are
    clipped by their true L2 norm; count-sketch tables (2-D) are clipped by
    the sketch's *estimate* of the vector norm — the median per-row table
    norm (``l2estimate()`` in csvec) — NOT the Frobenius norm, which is
    ~sqrt(r) larger and would over-clip. Scaling the table scales every
    row-norm estimate by the same factor, so the clipped table's estimated
    norm equals ``clip``.
    """
    if record.ndim == 2:
        l2 = jnp.median(jnp.linalg.norm(record, axis=1))
    else:
        l2 = jnp.linalg.norm(record)
    scale = jnp.where(l2 > clip, clip / jnp.maximum(l2, 1e-12), 1.0)
    return record * scale.astype(record.dtype)
