"""Stratified Subsampled Randomized Hadamard Transform (SRHT) — a
LOSSLESS-REGIME / DIAGNOSTIC transform, not a co-equal alternative to the
count sketches for compressing runs (it measurably diverges under FetchSGD
error feedback at r·c << d; see "Regime of validity"). Its practical roles:
exact-roundtrip configurations at r·c >= d, where its MXU Hadamard is the
fastest path, and reproducing the divergence study. For compressed training
use ``circ`` (default) or ``hash``.

Why this exists
---------------
The count sketch's encode/decode are O(d·r) random scatter/gathers; TPU
scatter/gather throughput is ~10-100M elements/s regardless of locality (the
op itself serializes), so at the reference's flagship config (d≈6.6M, r=5)
each encode or decode costs ~250 ms. This sketch provides the same linear-map
guarantees FetchSGD needs — linearity (tables sum across workers/psum),
unbiased per-coordinate estimates with variance ~||v||²/c, heavy-hitter
recovery via median-of-r — while using ONLY elementwise ops, reductions and
matmuls: no scatter, no gather, no sort anywhere. ~15 ms where the hash
sketch needs ~500 ms. It replaces the same external ``csvec.CSVec``
dependency (reference call sites CommEfficient/fed_worker.py:312-320,
fed_aggregator.py:464-467, 584-595) with different — strictly
TPU-friendlier — internals.

Regime of validity (IMPORTANT)
------------------------------
Safe only near the lossless regime r*c >= d. At real compression ratios
(r*c << d) FetchSGD error feedback DIVERGES with this sketch — measured
in tests/test_learning.py's sketch-regime study, on every topology and
with either error-feedback rule: SRHT decode noise is spread uniformly
(~||v||/sqrt(c) per coordinate), so top-k over the estimates stops being
a contraction of the accumulated error once the un-transmitted mass
dominates, and the error feedback loop explodes within tens of rounds.
The count-sketch cell-zeroing rule dissipates k/c of the table's error
mass every round and is stable — the default impl is the circulant count
sketch (``sketch_impl="circ"``, ops/circulant.py: cell semantics without
the scatter/gather cost), with ``"hash"`` as the exact-CSVec-semantics
variant; use rht for speed only when the sketch is sized
lossless-or-near (e.g. download-side compression, diagnostics,
r*c >= d configs).

Construction
------------
Row j of the sketch is  t_j = S_j · Ĥ · D_j · pad(v)  where

- D_j: diagonal ±1 Rademacher signs (precomputed int8 when small enough to
  be HBM-cheap, else derived on the fly from a murmur-mixed counter with
  FIXED shifts only — per-element variable shifts serialize on the VPU),
- Ĥ: the orthonormal Kronecker-Hadamard transform H_{n1}⊗H_{n2}⊗H_{n3} on the
  pow2-padded length d' = n1·n2·n3 ≥ d, applied as three last-axis matmuls
  (with layout rotations between) so every contraction is a well-tiled MXU
  matmul,
- S_j: a STRATIFIED sample — transformed coordinate i belongs to stratum
  (i mod c), i.e. stratum s = {s, s+c, s+2c, ...}, and each table cell holds
  one uniformly-chosen coordinate of its stratum. The interleaved partition
  keeps every one of the c strata within one coordinate of the same size for
  ANY c <= d' (a contiguous partition of width ceil(d'/c) would leave up to
  half the table structurally empty when c doesn't divide the pow2 size).
  Selection compiles to a fused compare(iota==offset)·multiply·reduce over
  the (m, c) view — no gather; the decode-side adjoint S_jᵀ is the same
  one-hot broadcast — no scatter.

Per-coordinate decode is the adjoint with per-stratum unbiasing scale
|stratum| (uniform-inclusion-probability correction):
est_j = D_j · Ĥ · S_jᵀ·diag(scale)·t_j.
The sketch estimate is the elementwise median over the r rows (a min/max
comparator network — the sort-based ``jnp.median`` costs >100 ms at this
size). Stratification only lowers estimator variance vs. uniform subsampling
(it guarantees even coverage). When c >= d' every stratum has one coordinate
(m == 1), S is the identity and the round-trip is EXACT: Ĥ(ĤDv) = v since Ĥ
is symmetric orthonormal — the analogue of a collision-free count sketch.

``encode``/``decode`` natively accept an optional leading batch axis (the
batch folds into the transform's row axis — a ``vmap`` over the un-batched
form would destroy the fused one-hot selection patterns).

The table shape is the same (r, c) as the hash sketch, so FedState /
transmitted-shape / upload-byte accounting are unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.ops.sketch import _mix32
from commefficient_tpu.ops.topk import median_axis0, topk_with_idx

_U32 = jnp.uint32

# precompute ±1 signs when the (r, d') table is at most this many entries
# (int8 => bytes, e.g. GPT-2: 5 x 134M = 670 MB — reading that back is ~1 ms
# where hashing 670M murmur mixes per encode costs ~450 ms); above it,
# recompute on the fly from the hash mixer instead of spending HBM
_PRECOMPUTE_SIGN_LIMIT = 1 << 30


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _kron_dims(dp: int) -> Tuple[int, ...]:
    """Factor the pow2 size dp into three roughly equal pow2 dims (so each
    matmul contraction is a well-shaped MXU operand, e.g. 2^23 -> 128x256x256)."""
    m = dp.bit_length() - 1
    a = m // 3
    b = (m - a) // 2
    return (1 << a, 1 << b, 1 << (m - a - b))


def _hadamard(n: int) -> np.ndarray:
    """Sylvester Hadamard matrix (±1 entries), n a power of two."""
    h = np.array([[1.0]], np.float32)
    while h.shape[0] < n:
        h = np.block([[h, h], [h, -h]])
    return h


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class RHTSketch:
    """Stratified SRHT sketch parameters. The (r, c) table itself is an
    ordinary array owned by the caller (lives in FedState, psums across the
    mesh, etc.)."""

    sign_keys: jax.Array    # (r,) uint32 (on-the-fly sign derivation)
    signs_i8: Optional[jax.Array]  # (r, dp) int8 ±1, or None (on-the-fly)
    offsets: jax.Array      # (r, c) int32: chosen member j of stratum s (coord j*c+s)
    scales: jax.Array       # (c,) f32: stratum size
    hadamards: Tuple[jax.Array, ...]  # the three (n_i, n_i) ±1 factors
    d: int
    c: int
    r: int
    dp: int                 # padded pow2 transform size, >= max(d, c)
    m: int                  # stratum width, ceil(dp / c)
    # transform compute dtype name ("float32" | "bfloat16"): bf16 halves the
    # HBM traffic of the three matmul passes; the ~1e-3 relative noise it
    # adds is far below the sketch's own estimation noise at any compressing
    # c < d (keep f32 when exact lossless round-trips matter)
    dtype: str = "float32"
    # process the r rows one at a time under lax.scan instead of as one
    # (B*r, dp) batch: peak transform memory drops r-fold. Auto-enabled for
    # large dp (GPT-2 scale: a batched (2*5, 2^27) f32 transform plus its
    # layout copies needs >16 GB HBM and OOMs a v5e chip)
    scan_rows: bool = False

    # server_update dispatches on this: a dense transform has no sparse
    # "occupied cells", so the table-space (mesh) branch uses subtractive
    # error feedback, while the single-device dense-preimage branch zeroes
    # the exact support (see core/server.py sketch branch for both)
    dense_transform = True

    def tree_flatten(self):
        return ((self.sign_keys, self.signs_i8, self.offsets, self.scales,
                 self.hadamards),
                (self.d, self.c, self.r, self.dp, self.m, self.dtype,
                 self.scan_rows))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def table_shape(self) -> Tuple[int, int]:
        return (self.r, self.c)

    def empty_table(self, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros(self.table_shape, dtype)

    # ------------------------------------------------------------ internals

    def _signs(self) -> jax.Array:
        """(r, dp) ±1 signs as float32."""
        if self.signs_i8 is not None:
            return self.signs_i8.astype(jnp.float32)
        # fixed-shift murmur per element (variable per-lane shifts serialize
        # on the TPU VPU — use the avalanched top bit instead)
        i = jnp.arange(self.dp, dtype=_U32)
        h = _mix32(i[None, :] * self.sign_keys[:, None] + _U32(0x9E3779B9))
        return 1.0 - 2.0 * (h >> 31).astype(jnp.float32)

    def _transform(self, y: jax.Array) -> jax.Array:
        """Orthonormal Kronecker-Hadamard over the last axis of (R, dp) for
        any row count R, as three last-axis matmuls with layout rotations in
        between (net layout change: identity)."""
        n1, n2, n3 = (h.shape[0] for h in self.hadamards)
        dt = jnp.dtype(self.dtype)
        h1, h2, h3 = (h.astype(dt) for h in self.hadamards)
        R = y.shape[0]
        x = y.astype(dt).reshape(R, n1, n2, n3)
        x = jnp.matmul(x.reshape(-1, n3), h3).reshape(R, n1, n2, n3)
        x = x.transpose(0, 1, 3, 2)
        x = jnp.matmul(x.reshape(-1, n2), h2).reshape(R, n1, n3, n2)
        x = x.transpose(0, 3, 2, 1)
        x = jnp.matmul(x.reshape(-1, n1), h1).reshape(R, n2, n3, n1)
        x = x.transpose(0, 3, 1, 2)
        return x.reshape(R, self.dp).astype(jnp.float32) * np.float32(
            1.0 / np.sqrt(self.dp))

    def _onehot(self) -> jax.Array:
        """(r, m, c) one-hot stratum-selection mask (fused into consumers);
        entry [row, j, s] selects transformed coordinate j*c + s."""
        return (jnp.arange(self.m, dtype=jnp.int32)[None, :, None]
                == self.offsets[:, None, :]).astype(jnp.float32)

    def _signs_row(self, j) -> jax.Array:
        """(dp,) ±1 signs of row j (j may be a tracer under lax.scan)."""
        if self.signs_i8 is not None:
            row = jax.lax.dynamic_index_in_dim(self.signs_i8, j, axis=0,
                                               keepdims=False)
            return row.astype(jnp.float32)
        i = jnp.arange(self.dp, dtype=_U32)
        key = jax.lax.dynamic_index_in_dim(self.sign_keys, j, axis=0,
                                           keepdims=False)
        h = _mix32(i * key + _U32(0x9E3779B9))
        return 1.0 - 2.0 * (h >> 31).astype(jnp.float32)

    def _onehot_row(self, j) -> jax.Array:
        """(m, c) one-hot mask of row j."""
        off = jax.lax.dynamic_index_in_dim(self.offsets, j, axis=0,
                                           keepdims=False)
        return (jnp.arange(self.m, dtype=jnp.int32)[:, None]
                == off[None, :]).astype(jnp.float32)

    # -------------------------------------------------------------------- api

    def encode(self, vec: jax.Array) -> jax.Array:
        """(d,) -> (r, c) table, or batched (B, d) -> (B, r, c)."""
        batched = vec.ndim == 2
        V = vec if batched else vec[None]
        B = V.shape[0]
        assert V.shape[1] == self.d, (vec.shape, self.d)
        v = jnp.pad(V.astype(jnp.float32), ((0, 0), (0, self.dp - self.d)))
        if self.scan_rows:
            def body(_, j):
                z = self._transform(self._signs_row(j)[None] * v)  # (B, dp)
                z = jnp.pad(z, ((0, 0), (0, self.c * self.m - self.dp)))
                z = z.reshape(B, self.m, self.c)
                return None, (z * self._onehot_row(j)[None]).sum(axis=1)
            _, ts = jax.lax.scan(body, None, jnp.arange(self.r))  # (r, B, c)
            t = ts.transpose(1, 0, 2)
        else:
            y = (self._signs()[None] * v[:, None, :]).reshape(
                B * self.r, self.dp)
            z = self._transform(y)
            z = jnp.pad(z, ((0, 0), (0, self.c * self.m - self.dp)))
            z = z.reshape(B, self.r, self.m, self.c)
            t = (z * self._onehot()[None]).sum(axis=2)
        return t if batched else t[0]

    def encode_at(self, vec: jax.Array, idx: jax.Array) -> jax.Array:
        """Sparse-support encode. The transform is dense, so this is just
        ``encode`` (provided for API parity with the hash sketch)."""
        del idx
        return self.encode(vec)

    def decode(self, table: jax.Array) -> jax.Array:
        """(r, c) -> (d,) median-of-r unbiased estimates of every coordinate;
        batched (B, r, c) -> (B, d)."""
        batched = table.ndim == 3
        T = table if batched else table[None]
        B = T.shape[0]
        assert T.shape[1:] == self.table_shape, (table.shape, self.table_shape)
        if self.scan_rows:
            dt = jnp.dtype(self.dtype)

            def body(_, j):
                tj = jax.lax.dynamic_index_in_dim(T, j, axis=1,
                                                  keepdims=False)  # (B, c)
                z = ((tj * self.scales[None, :])[:, None, :]
                     * self._onehot_row(j)[None])            # (B, m, c)
                z = z.reshape(B, self.c * self.m)[:, : self.dp]
                y = self._signs_row(j)[None] * self._transform(z)
                # store per-row estimates in the transform dtype: the
                # stacked (r, B, dp) buffer is the peak allocation here
                return None, y.astype(dt)
            _, ys = jax.lax.scan(body, None, jnp.arange(self.r))  # (r, B, dp)
            # median_axis0 reduces axis 0 with arbitrary trailing dims — no
            # transpose (which would materialize a second full-size copy in
            # exactly the memory-critical path scan_rows exists to shrink)
            est = median_axis0(ys.astype(jnp.float32))[:, : self.d]
        else:
            z = (T * self.scales[None, None, :])[:, :, None, :] \
                * self._onehot()[None]
            z = z.reshape(B * self.r, self.c * self.m)[:, : self.dp]
            y = self._signs()[None] * self._transform(z).reshape(
                B, self.r, self.dp)
            est = jax.vmap(median_axis0)(y)[:, : self.d]
        return est if batched else est[0]

    def unsketch_with_idx(self, table: jax.Array, k: int,
                          approx: bool = False):
        """Top-k heavy-hitter recovery (= CSVec.unSketch(k)) + support idx."""
        return topk_with_idx(self.decode(table), k, approx=approx)

    def unsketch(self, table: jax.Array, k: int, approx: bool = False):
        return self.unsketch_with_idx(table, k, approx)[0]

    def l2estimate(self, table: jax.Array) -> jax.Array:
        """||v|| estimate: E||t_j||² ≈ (c/dp)·||v||², so scale row norms by
        sqrt(dp/c) and take the median over rows (= CSVec.l2estimate())."""
        return jnp.median(jnp.linalg.norm(table, axis=1)) * np.float32(
            np.sqrt(self.dp / self.c))

    def clip(self, table: jax.Array, clip: float) -> jax.Array:
        """Scale the table so its *estimated* vector norm is <= clip
        (reference clip_grad on sketches, utils.py:305-313)."""
        l2 = self.l2estimate(table)
        scale = jnp.where(l2 > clip, clip / jnp.maximum(l2, 1e-12), 1.0)
        return table * scale


def make_rht_sketch(d: int, c: int, r: int, seed: int = 42,
                    dtype: str = "float32",
                    scan_rows: Optional[bool] = None) -> RHTSketch:
    """Build a stratified SRHT sketch for d-vectors with an (r, c) table.
    ``scan_rows`` defaults to automatic: row-at-a-time transforms once dp
    reaches 2^25 (large models), full-batch below."""
    dp = max(_next_pow2(d), _next_pow2(c))
    if scan_rows is None:
        scan_rows = dp >= (1 << 25)
    m = -(-dp // c)  # ceil: stratum width
    rng = np.random.RandomState(seed)
    sign_keys = rng.randint(1, 2**32, size=(r,),
                            dtype=np.uint64).astype(np.uint32) | 1
    signs_i8 = None
    if r * dp <= _PRECOMPUTE_SIGN_LIMIT:
        # int8 end to end: an int64 randint intermediate would transiently
        # cost 8x the final buffer (~5 GB host RAM at GPT-2 scale)
        signs_i8 = jnp.asarray(
            rng.randint(0, 2, size=(r, dp), dtype=np.int8) * 2 - 1)
    # interleaved stratum s = {s, s+c, s+2c, ...}: |stratum s| = #j with
    # j*c + s < dp — balanced within 1 across all c strata for any c <= dp.
    # Independent RNG stream: the offsets must not depend on whether the
    # sign table was precomputed above (same seed => same sketch either way)
    rng_off = np.random.RandomState(seed ^ 0x5EED5)
    sizes = -(-(dp - np.arange(c)) // c)
    offsets = rng_off.randint(0, sizes[None, :], size=(r, c)).astype(np.int32)
    hadamards = tuple(jnp.asarray(_hadamard(n)) for n in _kron_dims(dp))
    return RHTSketch(jnp.asarray(sign_keys), signs_i8,
                     jnp.asarray(offsets), jnp.asarray(sizes, jnp.float32),
                     hadamards, d=d, c=c, r=r, dp=dp, m=m, dtype=dtype,
                     scan_rows=scan_rows)
