"""Segment (per-group) reductions over the flat federated vector.

The layer-wise attribution layer (telemetry/layer_signals.py) reduces
dense (d,)-shaped round quantities — the aggregated gradient, the
applied update, the EF accumulators — into one small ``(G,)`` vector per
signal, where ``G`` is the number of named parameter groups. The
reduction is a scatter-add keyed by a precomputed int32 group-id map
(``gid[i]`` = the group owning ravel coordinate ``i``): O(d) work, no
``(G, d)`` one-hot materialization, and under GSPMD a sharded operand
pair reduces shard-locally into the replicated ``(G,)`` buckets with ONE
small psum — never a per-group collective unroll (the round-5 regression
class; the dryrun's collective ledger gates it).

Out-of-group coordinates (mesh ``d_pad`` padding) carry ``gid == G``,
which is out of bounds for the ``(G,)`` buckets and DROPPED by the
scatter — padding can never leak mass into a real group (pinned by
tests/test_layer_signals.py against a numpy reference).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _buckets(n_groups: int) -> jax.Array:
    return jnp.zeros((n_groups,), jnp.float32)


def group_sq_mass(x: jax.Array, gid: jax.Array,
                  n_groups: int) -> jax.Array:
    """Per-group squared-L2 mass (energy): ``out[g] = sum_{gid==g} x^2``.
    Conservation: ``out.sum() == ||x||^2`` up to fp addition order when
    every coordinate of ``x`` carries an in-range gid (padding
    coordinates of a mesh-padded vector are identically zero AND
    dropped, so either mechanism alone preserves the identity)."""
    x = x.astype(jnp.float32)
    return _buckets(n_groups).at[gid[: x.shape[0]]].add(
        x * x, mode="drop")


def group_count(mask: jax.Array, gid: jax.Array,
                n_groups: int) -> jax.Array:
    """Per-group count of True coordinates (e.g. the update's top-k
    support): ``out[g] = |{i : gid[i]==g and mask[i]}|`` as float32."""
    return _buckets(n_groups).at[gid[: mask.shape[0]]].add(
        mask.astype(jnp.float32), mode="drop")


def group_sum_cols(cols: jax.Array, gid: jax.Array,
                   n_groups: int) -> jax.Array:
    """Batched per-group sum of C stacked columns: ``cols`` is (L, C),
    the result (G, C) with ``out[g, j] = sum_{gid==g} cols[i, j]`` —
    ONE scatter (and on a mesh one (G*C,)-sized psum) for the whole
    signal family, instead of one collective per column."""
    return jnp.zeros((n_groups, cols.shape[-1]), jnp.float32).at[
        gid[: cols.shape[0]]].add(cols.astype(jnp.float32), mode="drop")


def group_sum_at(vals: jax.Array, idx: jax.Array, gid: jax.Array,
                 n_groups: int) -> jax.Array:
    """Segment-sum of ``vals`` over the groups owning the COORDINATES
    ``idx`` (the k top-k winner indices): ``out[g] = sum_{gid[idx[j]]==g}
    vals[j]``. O(k) gather + scatter — the winner-attribution primitive
    (counts when ``vals`` is all-ones, recovered-winner counts when it
    is the update's support at the winners)."""
    return _buckets(n_groups).at[gid[idx]].add(
        vals.astype(jnp.float32), mode="drop")
