"""Circulant count sketch — scatter/gather-free count sketch for TPU.

Third sketch implementation (``sketch_impl="circ"``, the default), designed
to combine the other two's strengths:

- the HASH count sketch (ops/sketch.py, exact CSVec semantics — reference
  call sites CommEfficient/fed_worker.py:312-320, fed_aggregator.py:584-595)
  is STABLE under FetchSGD error feedback at real compression ratios
  (cell-zeroing dissipates k/c of the table's error mass per round), but its
  encode/decode are O(d·r) random scatter/gathers — ~250 ms each at the
  flagship config (d≈6.6M, r=5) because TPU scatter/gather serializes;
- the SRHT sketch (ops/rht.py) runs on the MXU in ~15 ms but its
  uniformly-spread JL estimate noise makes top-k error feedback divergent
  whenever r·c << d (see ops/rht.py "Regime of validity").

Construction
------------
Pad d up to m·c and view the vector as m blocks of length c. Row j of the
table is

    t_j = sum_b  roll(sigma_{j,b} * v_b,  s_{j,b})

with per-(row, block) signs sigma (±1, derived on the fly from a murmur
mixer — never materialized at (r, d)) and per-(row, block) cyclic shifts
s_{j,b} drawn once from the seed. This is a genuine count sketch: the
bucket map h_j(b, i) = (i + s_{j,b}) mod c satisfies

- P[h_j(b,i) = h_j(b',i')] = 1/c for b != b' (uniform independent shifts),
- coordinates of the SAME block never collide (strictly better than the
  2-universal bound),

so per-row estimates sigma_{j,b}[i] * t_j[h_j(b,i)] are unbiased with
variance <= ||v||^2/c, and the median over r independent rows gives the
standard CountSketch heavy-hitter guarantee. When c >= d (m = 1) the
round-trip is exact (a roll is invertible), matching the other impls'
lossless limit.

Why it is fast on TPU: the shifts are STATIC (python ints baked at trace
time), so every ``jnp.roll`` compiles to two contiguous slices + concat —
pure HBM-bandwidth data movement, no scatter, no gather, no sort. Encode =
r·(sign-multiply + m static rolls + reduce); decode = r·m static rolls of
the (c,) table rows + sign-multiply + median-of-r comparator network.
Measured at the flagship CV config: ~5 ms vs the hash impl's ~250 ms per
op. When c % 1024 == 0 the shifts are additionally drawn at vreg
granularity (see ``make_circulant_sketch`` for why the statistics are
unchanged) and decode runs as a fused Pallas kernel
(ops/circulant_pallas.py — 21 ms vs the roll path's 129 ms at the GPT-2
scale d=124M, where r·m static roll OPS otherwise dominate at ~70 us of
fixed XLA per-op cost each).

Error feedback: a k-sparse update encodes into <= k·r occupied cells, and
``dense_transform = False``, so the server applies the reference's exact
cell-zeroing rule (fed_aggregator.py:596-611) — the stable dynamics, same
as the hash impl (validated at r·c << d in tests/test_learning.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.ops.sketch import _mix32, loop_token_zero
from commefficient_tpu.ops.topk import (clip_by_l2_norm, median_axis0, topk,
                                        topk_with_idx)

_U32 = jnp.uint32


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CirculantSketch:
    """(d -> r x c) circulant count sketch.

    ``shifts`` is a static tuple-of-tuples (r, m) of python ints — part of
    the pytree aux data so every ``roll`` gets a compile-time shift. Sign
    keys are arrays (jit arguments, like the hash impl's keys).
    """

    sign_keys: jax.Array            # (r,) uint32
    shifts: Tuple[Tuple[int, ...], ...]  # (r, m) static
    d: int
    c: int
    r: int
    num_blocks: int                 # decode memory chunking over the m axis
    # pallas kernel policy (config.py --pallas): "auto"/"on" = fused
    # encode AND decode when eligible (both measured wins under the
    # fused-clients round), "off" = XLA paths only
    pallas: str = "auto"

    dense_transform = False

    def tree_flatten(self):
        return ((self.sign_keys,),
                (self.shifts, self.d, self.c, self.r, self.num_blocks,
                 self.pallas))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    # ------------------------------------------------------------- layout

    @property
    def m(self) -> int:
        return -(-self.d // self.c)  # ceil: number of length-c blocks

    @property
    def table_shape(self) -> Tuple[int, int]:
        return (self.r, self.c)

    def empty_table(self, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros(self.table_shape, dtype)

    def _sign_of(self, row: int, idx: jax.Array) -> jax.Array:
        """±1 sign of global coordinates ``idx`` in ``row`` — the ONE
        definition of the sign stream (murmur mixer, ops/sketch.py);
        encode, decode and encode_at must all agree on it."""
        h = _mix32(idx.astype(_U32) * self.sign_keys[row]
                   + _U32(0x9E3779B9))
        return 1.0 - 2.0 * (h >> 31).astype(jnp.float32)

    def _signs(self, row: int, b0: int = 0,
               nb: Optional[int] = None) -> jax.Array:
        """±1 signs for blocks [b0, b0+nb) of one row — no (r, d) table,
        and decode chunks only ever materialize their own block range."""
        nb = self.m - b0 if nb is None else nb
        idx = b0 * self.c + jnp.arange(nb * self.c, dtype=_U32)
        return self._sign_of(row, idx).reshape(nb, self.c)

    # ---------------------------------------------------------------- ops

    # above this many blocks the unrolled static rolls stop paying off:
    # tracing/compile time scales with m, so switch to one (m, c) gather
    # per row (same semantics; only arises at extreme d/c ratios — the
    # flagship configs have m <= ~250)
    _UNROLL_MAX_BLOCKS = 512

    def _row_shift_idx(self, j: int, sign: int, b0: int = 0,
                       nb: Optional[int] = None) -> jax.Array:
        """(nb, c) column indices implementing per-block rolls by
        ``sign * shifts[j]`` for blocks [b0, b0+nb) as one
        take_along_axis."""
        nb = self.m - b0 if nb is None else nb
        s = jnp.asarray(self.shifts[j][b0:b0 + nb], jnp.int32)[:, None]
        k = jnp.arange(self.c, dtype=jnp.int32)[None, :]
        return (k - sign * s) % self.c

    def _pallas_eligible(self) -> bool:
        """Fused pallas kernels (ops/circulant_pallas.py) need: TPU
        backend, a SHIFT_ALIGN-granular column count AND shift table
        (``make_circulant_sketch`` generates aligned shifts whenever
        c % 1024 == 0 — the reference's default c=500,000 = 2^5·5^6 can
        never align; pick e.g. --num_cols 524288), and the wrap-padded
        table within the decode kernel's VMEM residency budget.
        ``--pallas off`` disables outright."""
        if (self.m <= 1 or self.pallas == "off"
                or jax.default_backend() != "tpu"):
            return False
        from commefficient_tpu.ops.circulant_pallas import (
            SHIFT_ALIGN, TABLE_VMEM_BUDGET, _lane_tile)
        if self.c % SHIFT_ALIGN:
            return False
        if any(s % SHIFT_ALIGN for row in self.shifts for s in row):
            return False
        return 4 * self.r * (self.c + _lane_tile(self.c)) \
            <= TABLE_VMEM_BUDGET

    def _use_pallas_decode(self) -> bool:
        # default ON when eligible: measured 21 ms vs the roll path's
        # 129 ms at the flagship d=124M config
        return self._pallas_eligible()

    def _use_pallas_encode(self) -> bool:
        # ON when eligible (round 4): with the fused-clients round (ONE
        # encode of the summed gradient per round), the pallas encode
        # measured 429 -> 385 ms on the flagship GPT-2 round (76.5k ->
        # 85.2k tok/s) vs the XLA static-roll path. (Under the old
        # per-client vmap encode the two were ~equal, which is why this
        # began opt-in.) Kept as a separate seam from decode in case the
        # two policies ever diverge again.
        return self._pallas_eligible()

    def encode(self, vec: jax.Array) -> jax.Array:
        assert vec.ndim == 1 and vec.shape[0] == self.d, (vec.shape, self.d)
        m, c = self.m, self.c
        if self._use_pallas_encode():
            from commefficient_tpu.ops.circulant_pallas import pallas_encode
            vp = jnp.pad(vec.astype(jnp.float32), (0, m * c - self.d))
            return pallas_encode(vp, jnp.asarray(self.shifts, jnp.int32),
                                 self.sign_keys, c=c, r=self.r, m=m)
        vp = jnp.pad(vec.astype(jnp.float32), (0, m * c - self.d)).reshape(
            m, c)
        rows = []
        for j in range(self.r):
            sv = self._signs(j) * vp                       # (m, c)
            if m <= self._UNROLL_MAX_BLOCKS:
                # static per-block rolls: slice+slice+concat each
                rolled = jnp.stack(
                    [jnp.roll(sv[b], self.shifts[j][b]) for b in range(m)])
            else:
                rolled = jnp.take_along_axis(
                    sv, self._row_shift_idx(j, sign=1), axis=1)
            rows.append(rolled.sum(axis=0))
        return jnp.stack(rows)

    def encode_accum(self, table: jax.Array, vals: jax.Array,
                     start: int = 0, scale=None,
                     token: Optional[jax.Array] = None) -> jax.Array:
        """Accumulating range encode: ``table + encode(v)`` for the
        vector ``v`` holding ``vals`` at global coordinates
        ``[start, start + len(vals))`` and zero elsewhere — without ever
        materializing a (d,)-sized buffer (only this range's blocks are
        resident). The streaming entry point of the fused-encode client
        path (core/client.py): per-microbatch gradients accumulate into
        the O(r·c) carry, chunk by chunk.

        ``start`` must be a STATIC python int (the per-block shifts are
        compile-time constants — that is what makes the roll path
        scatter-free; a traced-offset caller should use
        :meth:`encode_vals_at`, whose bucket map is pure arithmetic).
        ``scale`` multiplies the values before encoding (linearity);
        ``token`` is any loop-varying scalar defeating while-loop sign
        hoisting (ops/sketch.py loop_token_zero). The whole-vector call
        (``start == 0``, full d) routes through the fused Pallas encode
        kernel when eligible — the accumulate is then one table add."""
        assert vals.ndim == 1, vals.shape
        assert table.shape == self.table_shape, (table.shape,
                                                 self.table_shape)
        start = int(start)
        assert start >= 0 and start + vals.shape[0] <= self.m * self.c, (
            start, vals.shape, self.d)
        vals = vals.astype(jnp.float32)
        if scale is not None:
            vals = vals * scale
        m, c = self.m, self.c
        if start == 0 and vals.shape[0] == self.d \
                and self._use_pallas_encode():
            from commefficient_tpu.ops.circulant_pallas import pallas_encode
            vp = jnp.pad(vals, (0, m * c - self.d))
            return table + pallas_encode(
                vp, jnp.asarray(self.shifts, jnp.int32), self.sign_keys,
                c=c, r=self.r, m=m)
        n = vals.shape[0]
        b0 = start // c
        o0 = start - b0 * c
        nb = -(-(o0 + n) // c)
        vp = jnp.pad(vals, (o0, nb * c - o0 - n)).reshape(nb, c)
        zu = loop_token_zero(token)
        # the token is folded into the SCALAR offset before it meets the
        # iota: written ``const + arange + zu`` (left-assoc), the
        # ``const + arange`` pair is a nullary all-constant fusion XLA
        # hoists out of the scan and keeps resident for every range at
        # once (measured: L per-layer u32 base vectors alive together on
        # the streaming-backward path); ``arange + (zu + const)`` keeps
        # every index vector data-dependent on the loop-varying token
        idx0 = jnp.arange(nb * c, dtype=_U32) + (zu + _U32(b0 * c))
        for j in range(self.r):
            signs = self._sign_of(j, idx0).reshape(nb, c)
            sv = signs * vp
            if nb <= self._UNROLL_MAX_BLOCKS:
                rolled = jnp.stack(
                    [jnp.roll(sv[b], self.shifts[j][b0 + b])
                     for b in range(nb)])
            else:
                rolled = jnp.take_along_axis(
                    sv, self._row_shift_idx(j, sign=1, b0=b0, nb=nb),
                    axis=1)
            table = table.at[j].add(rolled.sum(axis=0))
        return table

    def _buckets_of(self, j: int, idx: jax.Array) -> jax.Array:
        """Bucket of global coordinate i in row j:
        (i mod c + shifts[j][i // c]) mod c — the ONE definition shared by
        encode_at and decode_at (signs come from ``_sign_of``)."""
        s = jnp.asarray(self.shifts[j], jnp.int32)[idx // self.c]
        return (idx.astype(jnp.int32) % self.c + s) % self.c

    def encode_at(self, vec: jax.Array, idx: jax.Array) -> jax.Array:
        """Encode a k-sparse vector given its support indices: equals
        ``encode(vec)`` when vec is zero outside ``idx``, at O(k·r)
        scatter-add cost instead of the O(d·r) roll pass (~2 ms vs ~87 ms
        at d=124M, k=50k — this runs every round for the server's
        error-feedback re-encode)."""
        return self.encode_vals_at(vec[idx], idx)

    def encode_vals_at(self, vals: jax.Array, idx: jax.Array) -> jax.Array:
        """``encode_at`` taking the k support VALUES directly — no dense
        (d,) staging buffer (the subtractive-EF momentum masking's path,
        core/server.py)."""
        rows = []
        for j in range(self.r):
            rows.append(jax.ops.segment_sum(self._sign_of(j, idx) * vals,
                                            self._buckets_of(j, idx),
                                            num_segments=self.c))
        return jnp.stack(rows)

    def decode_at(self, table: jax.Array, idx: jax.Array) -> jax.Array:
        """Median-of-r estimates of the coordinates ``idx`` only: equals
        ``decode(table)[idx]`` at O(k·r) gather cost instead of the O(d·r)
        full decode (used by the subtractive error-feedback rule's
        momentum masking, core/server.py)."""
        ests = []
        for j in range(self.r):
            ests.append(self._sign_of(j, idx)
                        * table[j, self._buckets_of(j, idx)])
        return median_axis0(jnp.stack(ests))

    def decode_range(self, table: jax.Array, start, length: int
                     ) -> jax.Array:
        """Median-of-r estimates of the ``length`` contiguous
        coordinates starting at global index ``start``: equals
        ``decode(table)[start:start+length]`` for coordinates < d, and
        EXACTLY 0 beyond d (mesh padding must never win a top-k).

        ``start`` may be a TRACED scalar (the sharded server tail's
        ``axis_index``-dependent slice, core/server.py) — the static
        per-block shifts cannot be selected at trace time then, so this
        runs the ``decode_at`` gather form (the ONE shared bucket/sign
        definition) chunk by chunk: peak memory O(r * chunk), no
        (d,)-sized buffer. Same estimate values as the static-roll
        decode — rolls and gathers move the same table cells.
        """
        assert table.shape == self.table_shape, (table.shape,
                                                 self.table_shape)
        assert length >= 1, length
        start = jnp.asarray(start, jnp.int32)
        bl = min(self.c, length)
        nb = -(-length // bl)
        base = jnp.arange(bl, dtype=jnp.int32)

        def body(_, off):
            idx = start + off + base          # (bl,) global coordinates
            ests = jnp.stack([self._sign_of(j, idx)
                              * table[j, self._buckets_of(j, idx)]
                              for j in range(self.r)])
            return None, jnp.where(idx < self.d, median_axis0(ests), 0.0)

        if nb == 1:
            return body(None, jnp.int32(0))[1][:length]
        _, ests = jax.lax.scan(body, None,
                               jnp.arange(nb, dtype=jnp.int32) * bl)
        return ests.reshape(-1)[:length]

    def decode(self, table: jax.Array) -> jax.Array:
        assert table.shape == self.table_shape, (table.shape,
                                                 self.table_shape)
        m, c = self.m, self.c
        if self._use_pallas_decode():
            from commefficient_tpu.ops.circulant_pallas import pallas_decode
            return pallas_decode(table, jnp.asarray(self.shifts, jnp.int32),
                                 self.sign_keys, c=c, r=self.r,
                                 m=m)[: self.d]
        # chunk the m axis so peak memory is O(r * m/num_blocks * c) on
        # both implementations of the per-block shift
        chunk = max(1, -(-m // max(1, self.num_blocks)))
        outs = []
        for b0 in range(0, m, chunk):
            mb = min(chunk, m - b0)
            if m > self._UNROLL_MAX_BLOCKS:
                ests = jnp.stack([
                    jnp.take_along_axis(
                        jnp.broadcast_to(table[j], (mb, c)),
                        self._row_shift_idx(j, sign=-1, b0=b0, nb=mb),
                        axis=1)
                    for j in range(self.r)])              # (r, mb, c)
            else:
                ests = jnp.stack([
                    jnp.stack([jnp.roll(table[j], -self.shifts[j][b])
                               for b in range(b0, b0 + mb)])
                    for j in range(self.r)])              # (r, mb, c)
            signs = jnp.stack(
                [self._signs(j, b0, mb) for j in range(self.r)])
            outs.append(median_axis0(ests * signs).reshape(-1))
        return jnp.concatenate(outs)[: self.d]

    def unsketch(self, table: jax.Array, k: int, approx: bool = False):
        return topk(self.decode(table), k, approx=approx)

    def unsketch_with_idx(self, table: jax.Array, k: int,
                          approx: bool = False):
        return topk_with_idx(self.decode(table), k, approx=approx)

    def l2estimate(self, table: jax.Array) -> jax.Array:
        return jnp.median(jnp.linalg.norm(table, axis=1))

    def clip(self, table: jax.Array, clip: float) -> jax.Array:
        return clip_by_l2_norm(table, clip)

    # --wire_dtype int8 entry points (ops/wire.py): the wire quantizes
    # TABLE CELLS, so it is sketch-impl-agnostic — mirrored on
    # CountSketch so wire consumers stay implementation-blind
    def quantize_wire(self, table: jax.Array, block: int, *, seed: int,
                      round_idx, salt=0):
        from commefficient_tpu.ops.wire import quantize_table
        return quantize_table(table, block, seed=seed,
                              round_idx=round_idx, salt=salt)

    def dequantize_wire(self, q: jax.Array, scale: jax.Array,
                        block: int) -> jax.Array:
        from commefficient_tpu.ops.wire import dequantize_table
        return dequantize_table(q, scale, block)


def make_circulant_sketch(d: int, c: int, r: int, num_blocks: int = 1,
                          seed: int = 42,
                          pallas: str = "auto") -> CirculantSketch:
    """Shift granularity: when c % 1024 == 0, shifts are drawn as uniform
    MULTIPLES of 1024 (= 8 sublanes x 128 lanes). That makes every span
    of a per-block roll start on a TPU vreg boundary, which is what lets
    the pallas decode kernel extract it with one sublane-dynamic slice
    instead of a dynamic rotate (ops/circulant_pallas.py v4 — measured
    6x). Statistics under the coarser shifts: two coordinates i (block
    b), i' (block b') collide iff s_b − s_b' ≡ i' − i (mod c), which has
    probability 1024/c when i ≡ i' (mod 1024) and 0 otherwise — the
    bucket map partitions coordinates into residue classes mod 1024,
    colliding 1024x more often within a class and never across. Averaged
    over coordinates the per-row estimate variance is still ≤ ||v||²/c,
    but it is NOT the per-pair 1/c bound: a vector whose heavy
    coordinates concentrate in one residue class sees up to 1024x the
    per-row variance, and because the class partition is shared by every
    row (alignment is what the pallas kernel needs, so it cannot be
    de-correlated per row), the median over rows does not restore the
    worst case. Model gradients have no mechanism tying magnitude to
    i mod 1024 of the flattened parameter index, which is why the
    aligned construction is the default for aligned c — but a user who
    needs the exact CountSketch per-pair guarantee should pick an
    unaligned c (e.g. the reference's 500,000), which keeps 1-granular
    shifts at the cost of the fused pallas decode. (Same-block
    coordinates still never collide, in either construction.)"""
    rng = np.random.RandomState(seed)
    m = -(-d // c)
    if m > CirculantSketch._UNROLL_MAX_BLOCKS:
        import warnings
        warnings.warn(
            f"circulant sketch with m = ceil(d/c) = {m} blocks exceeds "
            f"_UNROLL_MAX_BLOCKS={CirculantSketch._UNROLL_MAX_BLOCKS}: "
            "encode/decode fall back from static rolls to a "
            "take_along_axis gather, which is ~100x slower on TPU "
            "(measured 2,673 ms/op at d=124M in the gather regime vs "
            "26 ms static-roll encode). Increase num_cols so that "
            "d/num_cols <= 512.", stacklevel=2)
    if c % 1024 == 0:
        shifts = tuple(
            tuple(int(s) * 1024 for s in rng.randint(0, c // 1024, size=m))
            for _ in range(r))
    else:
        shifts = tuple(tuple(int(s) for s in rng.randint(0, c, size=m))
                       for _ in range(r))
    sign_keys = rng.randint(0, 2**32, size=(r,),
                            dtype=np.uint64).astype(np.uint32) | 1
    return CirculantSketch(jnp.asarray(sign_keys), shifts, d=d, c=c, r=r,
                           num_blocks=num_blocks, pallas=pallas)
