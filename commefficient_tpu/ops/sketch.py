"""Count Sketch for gradient compression, XLA-native.

Replaces the external ``csvec.CSVec`` CUDA package the reference depends on
(call sites: CommEfficient/fed_worker.py:312-320, fed_aggregator.py:464-467,
584-595, utils.py:309; the reference README says "To use sketching, you need
to install https://github.com/nikitaivkin/csh").

Semantics provided (matching the CSVec API surface):
- ``sketch_encode``   ~ ``CSVec.accumulateVec`` from a zeroed table: hash each of
  the d coordinates into one of c buckets per row with a ±1 sign, r rows.
- table addition      ~ ``accumulateTable``: tables are plain arrays; the sketch
  is LINEAR, so summing worker tables over the mesh (psum) equals sketching
  the summed gradient — this is what makes FetchSGD aggregation work.
- ``sketch_decode``   : median-of-r signed estimates for every coordinate.
- ``sketch_unsketch`` ~ ``CSVec.unSketch(k)``: dense vector holding the top-k
  estimated-magnitude coordinates (estimated values at those coordinates).
- ``sketch_l2estimate`` ~ ``CSVec.l2estimate()``: median per-row table norm.

TPU-first design decisions:
- Hash/sign index tables are NEVER materialized at (r, d) size (for GPT-2,
  d≈124M × r=5 would be 2.5 GB). Bucket/sign assignments are recomputed on the
  fly from a murmur-style 32-bit integer mixer — pure vector ALU ops that XLA
  fuses into the scatter/gather, trading negligible compute for HBM.
- ``num_blocks`` chunks the coordinate axis; encode/decode ``lax.scan`` over
  blocks so peak memory is O(d/num_blocks · r + r·c) regardless of d.
- Encode is a per-row ``segment_sum`` (scatter-add); decode is a gather +
  median. Both are static-shape and fully jittable/vmappable/shardable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from commefficient_tpu.ops.topk import (clip_by_l2_norm, median_axis0, topk,
                                        topk_with_idx)

_U32 = jnp.uint32


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CountSketch:
    """Hash-parameterization of a (d -> r x c) count sketch.

    Holds only the per-row 32-bit hash keys; the table itself is an ordinary
    ``(r, c)`` array owned by the caller (so it can live inside optimizer
    state, be psum'd, etc.).
    """

    bucket_keys: jax.Array  # (r,) uint32
    sign_keys: jax.Array    # (r,) uint32
    d: int
    c: int
    r: int
    num_blocks: int

    def tree_flatten(self):
        return (self.bucket_keys, self.sign_keys), (self.d, self.c, self.r, self.num_blocks)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    # the hash sketch of a k-sparse vector is k·r-sparse in table cells, so
    # the server's error feedback can zero "occupied cells" exactly as the
    # reference does (contrast RHTSketch.dense_transform)
    dense_transform = False

    @property
    def block_len(self) -> int:
        return -(-self.d // self.num_blocks)  # ceil

    @property
    def table_shape(self) -> Tuple[int, int]:
        return (self.r, self.c)

    def empty_table(self, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros(self.table_shape, dtype)

    # uniform method API shared with ops.rht.RHTSketch, so the runtime and
    # server are implementation-agnostic
    def encode(self, vec: jax.Array) -> jax.Array:
        return sketch_encode(self, vec)

    def encode_accum(self, table: jax.Array, vals: jax.Array,
                     start: int = 0, scale=None,
                     token: Optional[jax.Array] = None) -> jax.Array:
        return sketch_encode_accum(self, table, vals, start=start,
                                   scale=scale, token=token)

    def encode_at(self, vec: jax.Array, idx: jax.Array) -> jax.Array:
        return sketch_encode_at(self, vec, idx)

    def encode_vals_at(self, vals: jax.Array, idx: jax.Array) -> jax.Array:
        return sketch_encode_vals_at(self, vals, idx)

    def decode(self, table: jax.Array) -> jax.Array:
        return sketch_decode(self, table)

    def decode_at(self, table: jax.Array, idx: jax.Array) -> jax.Array:
        return sketch_decode_at(self, table, idx)

    def decode_range(self, table: jax.Array, start, length: int
                     ) -> jax.Array:
        return sketch_decode_range(self, table, start, length)

    def unsketch(self, table: jax.Array, k: int, approx: bool = False):
        return sketch_unsketch(self, table, k, approx=approx)

    def unsketch_with_idx(self, table: jax.Array, k: int,
                          approx: bool = False):
        return sketch_unsketch_with_idx(self, table, k, approx=approx)

    def l2estimate(self, table: jax.Array) -> jax.Array:
        return sketch_l2estimate(self, table)

    def clip(self, table: jax.Array, clip: float) -> jax.Array:
        """Scale the table so its estimated vector norm is <= clip; the hash
        sketch's norm estimate is the median per-row table norm, which is
        exactly the 2-D branch of clip_by_l2_norm."""
        return clip_by_l2_norm(table, clip)

    # --wire_dtype int8 entry points (ops/wire.py): the wire quantizes
    # TABLE CELLS, so it is sketch-impl-agnostic — these exist so wire
    # consumers stay implementation-blind like every other table op
    def quantize_wire(self, table: jax.Array, block: int, *, seed: int,
                      round_idx, salt=0):
        from commefficient_tpu.ops.wire import quantize_table
        return quantize_table(table, block, seed=seed,
                              round_idx=round_idx, salt=salt)

    def dequantize_wire(self, q: jax.Array, scale: jax.Array,
                        block: int) -> jax.Array:
        from commefficient_tpu.ops.wire import dequantize_table
        return dequantize_table(q, scale, block)


def make_sketch(d: int, c: int, r: int, num_blocks: int = 1,
                seed: int = 42) -> CountSketch:
    """Build deterministic hash keys for a (d, c, r) count sketch.

    Mirrors ``CSVec(d, c, r, numBlocks)`` (reference fed_aggregator.py:464-467)
    except the device argument: placement is the caller's sharding concern.
    """
    rng = np.random.RandomState(seed)
    bucket_keys = rng.randint(0, 2**32, size=(r,), dtype=np.uint64).astype(np.uint32) | 1
    sign_keys = rng.randint(0, 2**32, size=(r,), dtype=np.uint64).astype(np.uint32) | 1
    return CountSketch(jnp.asarray(bucket_keys), jnp.asarray(sign_keys),
                       d=d, c=c, r=r, num_blocks=num_blocks)


def make_sketch_impl(impl: str, d: int, c: int, r: int, num_blocks: int = 1,
                     seed: int = 42, dtype: str = "float32",
                     scan_rows: int = -1, pallas: str = "auto"):
    """Factory over the three sketch implementations: ``"circ"`` (circulant
    count sketch — stable cell-zeroing semantics AND scatter-free TPU speed,
    the default), ``"hash"`` (count sketch, exact CSVec semantics) or
    ``"rht"`` (SRHT, MXU matmuls; lossless-regime only — see ops/rht.py).
    ``dtype`` selects the rht transform compute dtype; ``scan_rows``: -1
    auto, 0 force batched, 1 force row-scanned; ``pallas`` is the circ
    impl's kernel policy (config.py --pallas: auto/on/off)."""
    if impl == "rht":
        from commefficient_tpu.ops.rht import make_rht_sketch
        return make_rht_sketch(d, c, r, seed=seed, dtype=dtype,
                               scan_rows=None if scan_rows < 0
                               else bool(scan_rows))
    if impl == "hash":
        return make_sketch(d, c, r, num_blocks, seed=seed)
    if impl == "circ":
        from commefficient_tpu.ops.circulant import make_circulant_sketch
        return make_circulant_sketch(d, c, r, num_blocks, seed=seed,
                                     pallas=pallas)
    raise ValueError(
        f"unknown sketch_impl {impl!r} (want 'circ', 'hash' or 'rht')")


def _mix32(h: jax.Array) -> jax.Array:
    """murmur3 fmix32 finalizer — avalanches all 32 bits so both the
    low-bits-dependent ``% c`` bucket map and the high-bit sign are well mixed."""
    h = h ^ (h >> 16)
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * _U32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _buckets_signs(cs: CountSketch, idx: jax.Array):
    """Per-row bucket ids and ±1 signs for global coordinate indices ``idx``.

    Returns (buckets (r, n) int32 in [0, c), signs (r, n) float32 ±1).
    """
    idx = idx.astype(_U32)[None, :]
    hb = _mix32(idx * cs.bucket_keys[:, None] + _U32(0x9E3779B9))
    hs = _mix32(idx * cs.sign_keys[:, None] + _U32(0x85EBCA77))
    buckets = (hb % _U32(cs.c)).astype(jnp.int32)
    signs = (1.0 - 2.0 * (hs >> 31).astype(jnp.float32))
    return buckets, signs


def sketch_encode(cs: CountSketch, vec: jax.Array) -> jax.Array:
    """Sketch a length-d vector into an (r, c) table (scatter-add per row)."""
    assert vec.ndim == 1 and vec.shape[0] == cs.d, (vec.shape, cs.d)
    bl, nb = cs.block_len, cs.num_blocks
    vec_p = jnp.pad(vec.astype(jnp.float32), (0, bl * nb - cs.d))
    blocks = vec_p.reshape(nb, bl)
    base = jnp.arange(bl, dtype=_U32)

    def body(table, args):
        b_idx, block_vals = args
        buckets, signs = _buckets_signs(cs, base + b_idx * _U32(bl))
        vals = signs * block_vals[None, :]
        contrib = jax.vmap(
            lambda b, v: jax.ops.segment_sum(v, b, num_segments=cs.c)
        )(buckets, vals)
        return table + contrib, None

    table, _ = lax.scan(body, cs.empty_table(),
                        (jnp.arange(nb, dtype=_U32), blocks))
    return table


def loop_token_zero(token: Optional[jax.Array]) -> jax.Array:
    """A uint32 zero that XLA cannot prove is zero, derived from any
    loop-varying scalar ``token`` (e.g. the microbatch loss).

    Why this exists: the streaming/accumulating encodes below recompute
    their ±1 sign streams from pure index arithmetic — loop-INVARIANT
    computations when the encode runs inside a ``lax.scan`` body. XLA's
    while-loop invariant code motion then hoists every sign tensor out
    of the scan and keeps all of them RESIDENT for the scan's whole
    lifetime (r x d floats — 3x the dense gradient the fused encode
    exists to kill; measured 6.7x d·4 temp on the CPU backend). Adding
    this opaque zero to the index stream makes the signs depend on the
    loop iteration, so they are recomputed per step (the module's design
    principle: vector ALU is cheaper than HBM residency).

    Robust to non-finite tokens: ``token * 0`` is NaN for inf/NaN
    inputs, so the NaN is explicitly squashed back to zero BEFORE the
    integer conversion — a diverging loss must never scramble bucket
    indices (quarantine/abort still see NaN table CELLS from the NaN
    values themselves). ``token=None`` returns a plain zero (no-op).
    """
    if token is None:
        return _U32(0)
    t0 = token.astype(jnp.float32) * 0.0
    t0 = jnp.where(jnp.isnan(t0), 0.0, t0)
    return lax.optimization_barrier(t0).astype(_U32)


def sketch_encode_accum(cs: CountSketch, table: jax.Array, vals: jax.Array,
                        start: int = 0, scale=None,
                        token: Optional[jax.Array] = None) -> jax.Array:
    """Accumulating range encode: add the sketch of a contiguous
    coordinate range to a carry ``table``.

    ``vals`` holds the values of global coordinates ``[start, start +
    len(vals))``; the result equals ``table + sketch_encode(cs, v)``
    for ``v`` zero outside the range (up to fp addition order). This is
    the streaming entry point the fused-encode client path accumulates
    per-microbatch gradients through (core/client.py): the carry is the
    O(r·c) table, and only this range's values are ever resident.
    ``scale`` multiplies the values before encoding (sketch linearity:
    ``encode(s*v) == s*encode(v)``); ``token`` see loop_token_zero.
    ``start`` may be a python int or a traced scalar (the hash bucket
    map is pure index arithmetic)."""
    assert vals.ndim == 1, vals.shape
    assert table.shape == cs.table_shape, (table.shape, cs.table_shape)
    vals = vals.astype(jnp.float32)
    if scale is not None:
        vals = vals * scale
    zu = loop_token_zero(token)
    n = vals.shape[0]
    bl = cs.block_len
    nb = -(-n // bl)
    vals_p = jnp.pad(vals, (0, nb * bl - n))
    # scalar (start + zu) first: see CirculantSketch.encode_accum — an
    # ``arange + start`` pair with a static start is an all-constant
    # fusion XLA hoists and keeps resident per call site
    base = (jnp.arange(bl, dtype=_U32)
            + (jnp.asarray(start).astype(_U32) + zu))

    def body(tbl, args):
        b_idx, block_vals = args
        buckets, signs = _buckets_signs(cs, base + b_idx * _U32(bl))
        sv = signs * block_vals[None, :]
        contrib = jax.vmap(
            lambda b, v: jax.ops.segment_sum(v, b, num_segments=cs.c)
        )(buckets, sv)
        return tbl + contrib, None

    if nb == 1:
        table, _ = body(table, (_U32(0), vals_p))
        return table
    table, _ = lax.scan(body, table,
                        (jnp.arange(nb, dtype=_U32),
                         vals_p.reshape(nb, bl)))
    return table


def sketch_decode(cs: CountSketch, table: jax.Array) -> jax.Array:
    """Median-of-r estimate of every coordinate; returns a dense (d,) vector."""
    assert table.shape == cs.table_shape, (table.shape, cs.table_shape)
    bl, nb = cs.block_len, cs.num_blocks
    base = jnp.arange(bl, dtype=_U32)
    rows = jnp.arange(cs.r)[:, None]

    def body(_, b_idx):
        buckets, signs = _buckets_signs(cs, base + b_idx * _U32(bl))
        ests = signs * table[rows, buckets]       # (r, bl)
        return None, median_axis0(ests)           # (bl,)

    _, ests = lax.scan(body, None, jnp.arange(nb, dtype=_U32))
    return ests.reshape(-1)[: cs.d]


def sketch_unsketch(cs: CountSketch, table: jax.Array, k: int,
                    approx: bool = False) -> jax.Array:
    """Top-k heavy-hitter recovery: dense (d,) vector, nonzero only at the k
    coordinates with the largest estimated magnitude (= ``CSVec.unSketch(k)``).
    ``approx`` uses the TPU approximate top-k (sketch estimates are already
    approximate, so the compounded error is benign)."""
    return topk(sketch_decode(cs, table), k, approx=approx)


def sketch_unsketch_with_idx(cs: CountSketch, table: jax.Array, k: int,
                             approx: bool = False):
    """`sketch_unsketch` that also returns the (k,) support indices, so the
    caller can re-sketch the k-sparse update with `sketch_encode_at` instead
    of a full d-coordinate encode (the reference re-sketches the dense update,
    fed_aggregator.py:593-595 — O(d) work for a k-sparse vector)."""
    return topk_with_idx(sketch_decode(cs, table), k, approx=approx)


def sketch_encode_at(cs: CountSketch, vec: jax.Array,
                     idx: jax.Array) -> jax.Array:
    """Encode a k-sparse vector given its support indices: exactly equals
    ``sketch_encode(cs, vec)`` when ``vec`` is zero outside ``idx``, but costs
    O(k·r) scatter updates instead of O(d·r)."""
    return sketch_encode_vals_at(cs, vec[idx], idx)


def sketch_encode_vals_at(cs: CountSketch, vals: jax.Array,
                          idx: jax.Array) -> jax.Array:
    """``sketch_encode_at`` taking the k support VALUES directly — no dense
    (d,) staging buffer (subtractive-EF momentum masking, core/server.py)."""
    buckets, signs = _buckets_signs(cs, idx.astype(_U32))
    svals = signs * vals[None, :]
    return jax.vmap(
        lambda b, v: jax.ops.segment_sum(v, b, num_segments=cs.c)
    )(buckets, svals)


def sketch_decode_at(cs: CountSketch, table: jax.Array,
                     idx: jax.Array) -> jax.Array:
    """Median-of-r estimates of the coordinates ``idx`` only: equals
    ``sketch_decode(cs, table)[idx]`` at O(k·r) gather cost (used by the
    subtractive error-feedback rule's momentum masking, core/server.py)."""
    buckets, signs = _buckets_signs(cs, idx.astype(_U32))
    rows = jnp.arange(cs.r)[:, None]
    return median_axis0(signs * table[rows, buckets])


def sketch_decode_range(cs: CountSketch, table: jax.Array, start,
                        length: int) -> jax.Array:
    """Median-of-r estimates of the ``length`` contiguous coordinates
    starting at global index ``start``: equals
    ``sketch_decode(cs, table)[start:start+length]`` for coordinates
    < d, and EXACTLY 0 beyond d (mesh-padding coordinates must never
    win a top-k against real estimates).

    ``start`` may be a python int or a TRACED scalar — the range
    restriction the sharded server tail needs (each device decodes only
    its ``axis_index``-dependent d_pad/n slice, core/server.py). The
    bucket/sign maps are pure index arithmetic, so a traced offset
    costs nothing; chunking via ``lax.scan`` bounds peak memory at
    O(r * block_len) exactly like the full decode.
    """
    assert table.shape == cs.table_shape, (table.shape, cs.table_shape)
    assert length >= 1, length
    start = jnp.asarray(start, jnp.int32)
    bl = min(cs.block_len, length)
    nb = -(-length // bl)
    rows = jnp.arange(cs.r)[:, None]
    base = jnp.arange(bl, dtype=jnp.int32)

    def body(_, off):
        idx = start + off + base              # (bl,) global coordinates
        buckets, signs = _buckets_signs(cs, idx.astype(_U32))
        ests = median_axis0(signs * table[rows, buckets])
        return None, jnp.where(idx < cs.d, ests, 0.0)

    if nb == 1:
        return body(None, jnp.int32(0))[1][:length]
    _, ests = lax.scan(body, None, jnp.arange(nb, dtype=jnp.int32) * bl)
    return ests.reshape(-1)[:length]


def sketch_l2estimate(cs: CountSketch, table: jax.Array) -> jax.Array:
    """Estimate of the L2 norm of the sketched vector (= ``CSVec.l2estimate``)."""
    return jnp.median(jnp.linalg.norm(table, axis=1))
