from commefficient_tpu.ops.topk import topk, clip_by_l2_norm
from commefficient_tpu.ops.pytree import ravel_params, make_unraveler
from commefficient_tpu.ops.sketch import (
    CountSketch,
    make_sketch,
    sketch_encode,
    sketch_decode,
    sketch_unsketch,
    sketch_l2estimate,
)

__all__ = [
    "topk",
    "clip_by_l2_norm",
    "ravel_params",
    "make_unraveler",
    "CountSketch",
    "make_sketch",
    "sketch_encode",
    "sketch_decode",
    "sketch_unsketch",
    "sketch_l2estimate",
]
