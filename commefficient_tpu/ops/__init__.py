from commefficient_tpu.ops.topk import (topk, topk_with_idx, median_axis0,
                                        clip_by_l2_norm)
from commefficient_tpu.ops.pytree import ravel_params, make_unraveler
from commefficient_tpu.ops.sketch import (
    CountSketch,
    make_sketch,
    make_sketch_impl,
    sketch_encode,
    sketch_decode,
    sketch_unsketch,
    sketch_l2estimate,
)
from commefficient_tpu.ops.rht import RHTSketch, make_rht_sketch
from commefficient_tpu.ops.wire import (WIRE_DTYPES, dequantize_table,
                                        quantize_table, wire_round_trip)

__all__ = [
    "WIRE_DTYPES",
    "quantize_table",
    "dequantize_table",
    "wire_round_trip",
    "topk",
    "topk_with_idx",
    "median_axis0",
    "clip_by_l2_norm",
    "ravel_params",
    "make_unraveler",
    "CountSketch",
    "make_sketch",
    "make_sketch_impl",
    "sketch_encode",
    "sketch_decode",
    "sketch_unsketch",
    "sketch_l2estimate",
    "RHTSketch",
    "make_rht_sketch",
]
