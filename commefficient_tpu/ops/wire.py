"""Int8 quantized wire for sketch tables (``--wire_dtype int8``).

The sketch table is the round's irreducible communication (PAPER.md
§2.1/§2.3 — the sketch is linear, so the table reduce IS the
aggregation), and after the PR-11 reduce-scatter the remaining lever is
the bytes per CELL on the ICI wire. This module owns the cell
arithmetic of that lever:

- :func:`quantize_table` — symmetric per-column-block abs-max int8
  quantization of an (r, c) table: each ``block`` consecutive columns
  of a row share one f32 scale ``absmax / 127``, and cells round
  STOCHASTICALLY (unbiased: ``E[q * scale] == x`` exactly) so the
  rounding noise is zero-mean and the server's error-feedback state
  absorbs it like any other compression noise instead of accumulating
  a bias.
- :func:`dequantize_table` / :func:`dequantize_accum` — the f32
  reconstruction, and the shard-local accumulate the quantized
  reduce-scatter uses (int8 summation over W clients would overflow at
  W >= 2; dequantize-then-add keeps the server momentum/EF numerics
  untouched).
- :func:`wire_round_trip` — quantize+dequantize in one call: the
  single-device simulation of the wire (what a client's upload looks
  like after the server decodes it).

Determinism contract: the stochastic-rounding draws come from a
counter-based hash (the murmur finalizer ops/sketch.py already uses for
bucket/sign streams — no PRNG key threading) keyed off ``(seed,
global_round, salt, cell)``, where ``salt`` distinguishes independent
quantizers in one round (the device index on a mesh, the client slot on
the per-client path). Replaying a round — including across a
kill/resume, where ``global_round`` comes back out of the checkpoint —
reproduces every draw bitwise; that is what makes the crash-resume gate
of ``__graft_entry__.dryrun_multichip`` hold for int8 runs.

Everything here is pure jnp (vector ALU only — the same
compute-over-residency trade the sketch hashing makes) and has an exact
numpy reference in tests/test_wire.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from commefficient_tpu.config import WIRE_DTYPES  # noqa: F401  (re-export)
from commefficient_tpu.ops.sketch import _mix32

_U32 = jnp.uint32

INT8_MAX = 127.0
# salt-namespace offset of the REDUCE quantizer (int8_reduce_scatter):
# per-client uploads salt by global slot index (0..W_total-1) and the
# mesh reduce by device index — without the offset, device j's
# partial-sum quantization would reuse client slot j's exact rounding
# stream in the same round (at 1 client/device the partial IS that
# client's dequantized table, and E[Q_u(Q_u(x))] != x — the shared
# draws break the per-quantizer unbiasedness the EF-absorption story
# rests on). 2^30 is far above any client universe.
REDUCE_SALT = 1 << 30
# bytes per table cell on the wire, plus (int8 only) 4 bytes per
# ``block`` cells of scale overhead — see FedConfig.upload_wire_bytes
WIRE_CELL_BYTES = {"float32": 4, "bfloat16": 2, "int8": 1}


def wire_uniform(r: int, c: int, *, seed: int, round_idx,
                 salt) -> jax.Array:
    """Deterministic U[0, 1) draws for every cell of an (r, c) table.

    Keyed off ``(seed, round_idx, salt, row, col)``: the static cell
    grid mixes with the static seed first, and the TRACED (round, salt)
    pair folds in afterwards — so XLA cannot constant-fold the draws
    (they genuinely change per round) but the per-cell stream is
    reproducible from the checkpointed round counter alone.
    """
    rows = jnp.arange(r, dtype=_U32)
    cols = jnp.arange(c, dtype=_U32)
    base = rows[:, None] * _U32(0x01000193) + cols[None, :]
    h = _mix32(base ^ (_U32(seed) * _U32(0x9E3779B1) + _U32(0x7F4A7C15)))
    rs = _mix32(jnp.asarray(round_idx).astype(_U32) * _U32(0x85EBCA77)
                + jnp.asarray(salt).astype(_U32) * _U32(0xC2B2AE3D))
    h = _mix32(h + rs)
    # 24 high-entropy bits -> [0, 1): exactly representable in f32, and
    # strictly < 1 so floor(x + u) can never round a whole number up
    return (h >> _U32(8)).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def quantize_table(table: jax.Array, block: int, *, seed: int,
                   round_idx, salt, stochastic: bool = True
                   ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-column-block abs-max int8 quantization.

    ``table`` is (r, c) f32 with ``c % block == 0``. Returns
    ``(q, scale)``: ``q`` (r, c) int8, ``scale`` (r, c // block) f32
    where ``scale = absmax(block) / 127``. Reconstruction is
    ``q * scale`` (:func:`dequantize_table`); with ``stochastic`` the
    rounding is ``floor(x / scale + u)`` for the keyed uniform ``u``,
    which is exactly unbiased per cell. An all-zero block keeps scale 0
    and quantizes to exact zeros; a non-finite cell poisons its block's
    scale (abs-max propagates NaN), so a diverging upload still trips
    the round's non-finite detection after dequantize — the wire never
    launders a NaN into a finite int8.
    """
    r, c = table.shape
    assert c % block == 0, (table.shape, block)
    g = table.astype(jnp.float32).reshape(r, c // block, block)
    absmax = jnp.max(jnp.abs(g), axis=2)
    scale = absmax / jnp.float32(INT8_MAX)
    # guard the division only: zero blocks divide by 1 and stay exact
    # zeros; NaN blocks keep their NaN scale (NaN > 0 is False, so the
    # divisor is 1 and the NaN cells flow into q's clip below — the
    # SCALE carries the poison to the dequantized output)
    safe = jnp.where(scale > 0, scale, 1.0)
    x = g / safe[:, :, None]
    if stochastic:
        u = wire_uniform(r, c, seed=seed, round_idx=round_idx, salt=salt)
        q = jnp.floor(x + u.reshape(r, c // block, block))
    else:
        q = jnp.round(x)
    # |x| <= 127 by construction; the clip only absorbs fp edge cases
    # of the abs-max division (and pins NaN to a harmless in-range
    # value — the NaN scale still poisons the reconstruction)
    q = jnp.clip(q, -INT8_MAX, INT8_MAX)
    return q.reshape(r, c).astype(jnp.int8), scale


def dequantize_table(q: jax.Array, scale: jax.Array,
                     block: int) -> jax.Array:
    """f32 reconstruction of :func:`quantize_table`'s output."""
    r, c = q.shape
    g = q.astype(jnp.float32).reshape(r, c // block, block)
    return (g * scale[:, :, None]).reshape(r, c)


def dequantize_accum(q: jax.Array, scale: jax.Array,
                     block: int) -> jax.Array:
    """Dequantize-accumulate a STACK of quantized contributions.

    ``q`` is (n, r, c) int8 — one contribution per source (the
    all_to_all'd per-device column shards of the quantized reduce) —
    and ``scale`` (n, r, c // block) their scales. Returns the f32 sum
    over the source axis: the accumulation happens in f32 AFTER
    dequantize (int8 summation over sources would overflow at the
    second contribution), in a fixed source order so the reduce is
    bitwise reproducible.
    """
    n, r, c = q.shape
    g = q.astype(jnp.float32).reshape(n, r, c // block, block)
    return (g * scale[..., None]).sum(axis=0).reshape(r, c)


def wire_round_trip(table: jax.Array, block: int, *, seed: int,
                    round_idx, salt) -> jax.Array:
    """Quantize + dequantize: the single-device simulation of one
    upload crossing the int8 wire. The difference ``table - result`` is
    the rounding residual the server error feedback absorbs."""
    q, scale = quantize_table(table, block, seed=seed,
                              round_idx=round_idx, salt=salt)
    return dequantize_table(q, scale, block)


def int8_reduce_scatter(agg: jax.Array, *, axis: str, n_shards: int,
                        block: int, seed: int, round_idx) -> jax.Array:
    """The quantized table reduce: what replaces ``psum_scatter`` under
    ``--wire_dtype int8`` (traced inside the round's ``shard_map``).

    Each device quantizes its LOCAL partial (r, c) table (salt = its
    axis index + REDUCE_SALT, so devices draw independent rounding
    noise in a namespace disjoint from the per-client uploads'), the int8
    column shards and their f32 scales travel by ``all_to_all`` (device
    j receives every device's shard j), and the receiver
    dequantize-accumulates in f32 — returning the (r, c / n) column
    shard of the summed table in the same layout ``psum_scatter``
    produced, so the sharded server tail consumes it unchanged. The
    optimization barriers pin the collectives' payload dtypes exactly
    like the bf16 wire's barrier: without them XLA may hoist the f32
    convert back through the (purely data-movement) all_to_all and the
    wire silently re-widens.
    """
    r, c = agg.shape
    shard_c = c // n_shards
    sb = shard_c // block
    # REDUCE_SALT keeps this quantizer's draw stream disjoint from the
    # per-client upload quantizers' slot-salted streams (see the
    # constant's comment)
    salt = lax.axis_index(axis) + REDUCE_SALT
    q, scale = quantize_table(agg, block, seed=seed, round_idx=round_idx,
                              salt=salt)
    q = lax.optimization_barrier(q)
    scale = lax.optimization_barrier(scale)
    q = lax.all_to_all(q.reshape(r, n_shards, shard_c), axis,
                       split_axis=1, concat_axis=1)
    scale = lax.all_to_all(scale.reshape(r, n_shards, sb), axis,
                           split_axis=1, concat_axis=1)
    # (r, n, shard_c) -> contributions on axis 1; accumulate in f32
    return dequantize_accum(jnp.moveaxis(q, 1, 0),
                            jnp.moveaxis(scale, 1, 0), block)
