"""Pallas TPU kernels for the circulant count sketch's encode/decode.

The jnp implementation in ops/circulant.py compiles the per-(row, block)
static rolls into r·m separate slice+concat HLO ops (1,250 at the GPT-2
config: m=250 blocks, r=5 rows) — measured ~70 us of fixed overhead per
op, i.e. ~87/103 ms per encode/decode at d=124M even though only ~7.5 GB
of HBM traffic is involved. These kernels fuse each direction into ONE
``pallas_call`` with a grid over 8-block superblocks: block DMAs
pipeline, the rotation is Mosaic's dynamic-shift ``pltpu.roll``, signs
come from the same murmur mixer computed in-kernel, and the (r, c)
accumulator (encode) / median network (decode) stay resident in VMEM.

STATUS: OPT-IN (``COMMEFFICIENT_PALLAS=1`` + TPU backend + c % 128 == 0;
see CirculantSketch._use_pallas). Semantics are identical to the roll
path — asserted in interpret mode by tests/test_ops.py and verified
against the TPU at small scale — but at d=124M the Mosaic compile was
observed not to terminate on the remote-compile path, so the roll path
remains the default.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from commefficient_tpu.ops.sketch import _mix32
from commefficient_tpu.ops.topk import median_axis0

_U32 = jnp.uint32
_GOLDEN = 0x9E3779B9


def _signs_block(b, c, key):
    """(1, c) ±1 signs of block b under sign key ``key`` — the same stream
    as CirculantSketch._sign_of."""
    idx = (b * c + jax.lax.broadcasted_iota(jnp.int32, (1, c), 1)
           ).astype(_U32)
    h = _mix32(idx * key + _U32(_GOLDEN))
    # Mosaic can't cast uint32 -> f32 directly; the top bit is 0/1 so an
    # int32 hop is exact
    return 1.0 - 2.0 * (h >> 31).astype(jnp.int32).astype(jnp.float32)


# TPU lowering requires block second-minor dims divisible by 8 (or equal
# to the array dim): process 8 coordinate-blocks per grid step
_SUPER = 8


def _encode_kernel(shifts_ref, keys_ref, v_ref, out_ref, *, c, r):
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    for jj in range(_SUPER):
        b = g * _SUPER + jj
        v = v_ref[jj:jj + 1, :]                          # (1, c)
        for j in range(r):
            sv = _signs_block(b, c, keys_ref[j]) * v     # (1, c)
            # Mosaic's dynamic-shift rotate (jnp.roll semantics)
            out_ref[j:j + 1, :] += pltpu.roll(sv, shifts_ref[j, b], axis=1)


def _decode_kernel(shifts_ref, keys_ref, t_ref, out_ref, *, c, r):
    g = pl.program_id(0)
    for jj in range(_SUPER):
        b = g * _SUPER + jj
        ests = []
        for j in range(r):
            # inverse rotation: roll by (c - s) mod c == roll by -s
            s = shifts_ref[j, b]
            rolled = pltpu.roll(t_ref[j:j + 1, :], (c - s) % c, axis=1)
            ests.append(_signs_block(b, c, keys_ref[j]) * rolled)
        out_ref[jj:jj + 1, :] = median_axis0(
            jnp.concatenate(ests, axis=0))[None]


def _pad_blocks(m):
    return -(-m // _SUPER) * _SUPER


@functools.partial(jax.jit, static_argnames=("c", "r", "m", "interpret"))
def pallas_encode(vec_padded, shifts, sign_keys, *, c, r, m,
                  interpret=False):
    """(m*c,) padded fp32 vector -> (r, c) table. ``shifts``: (r, m) int32;
    ``sign_keys``: (r,) uint32."""
    mp = _pad_blocks(m)
    blocks = jnp.pad(vec_padded.astype(jnp.float32),
                     (0, mp * c - m * c)).reshape(mp, c)
    # padded blocks carry zeros (contribution 0); their shifts just need
    # to exist and be in range
    shifts_p = jnp.pad(shifts, ((0, 0), (0, mp - m)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(mp // _SUPER,),
        in_specs=[pl.BlockSpec((_SUPER, c), lambda g, *_: (g, 0))],
        out_specs=pl.BlockSpec((r, c), lambda g, *_: (0, 0)),
    )
    return pl.pallas_call(
        functools.partial(_encode_kernel, c=c, r=r),
        out_shape=jax.ShapeDtypeStruct((r, c), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(shifts_p, sign_keys, blocks)


@functools.partial(jax.jit, static_argnames=("c", "r", "m", "interpret"))
def pallas_decode(table, shifts, sign_keys, *, c, r, m, interpret=False):
    """(r, c) table -> (m*c,) padded per-coordinate median estimates
    (trailing block-padding garbage is sliced off by the caller)."""
    mp = _pad_blocks(m)
    shifts_p = jnp.pad(shifts, ((0, 0), (0, mp - m)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(mp // _SUPER,),
        in_specs=[pl.BlockSpec((r, c), lambda g, *_: (0, 0))],
        out_specs=pl.BlockSpec((_SUPER, c), lambda g, *_: (g, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, c=c, r=r),
        out_shape=jax.ShapeDtypeStruct((mp, c), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(shifts_p, sign_keys, table.astype(jnp.float32))
    return out.reshape(-1)[: m * c]
