"""Pallas TPU kernels for the circulant count sketch's encode/decode.

The jnp implementation in ops/circulant.py compiles the per-(row, block)
static rolls into r·m separate slice+concat HLO ops (1,185 at the GPT-2
config: m=237 blocks, r=5 rows), each paying XLA's fixed per-op cost —
measured (chained on-device, d=124M, c=524288, v5e) ~26 ms encode and
~129 ms decode. These kernels fuse each direction into ONE
``pallas_call``.

Design history (all numbers measured the same way):
- v1 DMA'd whole (8, c) row-groups: 16 MB blocks double-buffered against
  ~16 MB VMEM — the Mosaic compile never terminated.
- v2 lane-tiled with two-tile gathers + dynamic ``pltpu.roll``:
  68/94 ms — DMA-descriptor-bound (19k small DMAs × ~5 us latency).
- v3 streamed big blocks / kept the table resident: 67/110 ms — the
  residual cost is the DYNAMIC ``pltpu.roll`` itself (Mosaic lowers a
  dynamic lane rotate as a multi-stage shift network; a 10-roll/step
  ablation costs +100 ms over the 23 ms copy floor).
- v4 (this file) eliminates rotates entirely: shifts are restricted to
  multiples of 1024 = 8 sublanes × 128 lanes (``make_circulant_sketch``
  applies that granularity whenever c % 1024 == 0 — see the statistics
  note there), so every span of a conceptual roll starts on a vreg
  boundary and comes out of a VMEM-resident, wrap-padded
  (rows, c/128 (+span), 128) view with ONE sublane-dynamic slice — pure
  address arithmetic, no data movement beyond the copy itself.
  Measured: decode 21 ms (6× over the roll path), with the whole table
  loaded into VMEM once (constant index map).

Exactness vs the roll path is asserted in interpret mode by
tests/test_ops.py and against numpy on the TPU at flagship scale.
Used AUTOMATICALLY for BOTH encode and decode on TPU when the sketch's
shifts are 1024-aligned and the wrap-padded table fits the VMEM
residency budget. (History: encode began opt-in — under the per-client
vmap round it measured ~equal to the XLA static-roll path; the round-4
fused-clients round encodes the summed gradient ONCE, where the pallas
encode lifts the flagship GPT-2 round 76.5k -> 85.2k tok/s.) The
``--pallas`` config flag controls the policy: ``off`` disables, ``auto``
(default) and ``on`` enable when eligible. Replaces the external CUDA
CSVec hot path (reference fed_worker.py:312-320).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from commefficient_tpu.ops.sketch import _mix32
from commefficient_tpu.ops.topk import median_axis0

_U32 = jnp.uint32
_GOLDEN = 0x9E3779B9

# shift granularity that makes every span start a whole number of vregs
# (8 sublanes x 128 lanes) into the row — the no-rotate enabler
SHIFT_ALIGN = 1024

# decode keeps the wrap-padded (r, c/128 + ct/128, 128) table resident in
# VMEM: cap its footprint (bytes) under the ~16 MB/core budget with room
# for temporaries
TABLE_VMEM_BUDGET = 12 << 20

# lane-tile width of the streamed output/input spans
_CT_MAX = 65536


def _lane_tile(c: int) -> int:
    """Largest divisor of c that is a multiple of SHIFT_ALIGN and ≤
    _CT_MAX. Callers guarantee c % SHIFT_ALIGN == 0, so SHIFT_ALIGN
    itself is always a valid fallback."""
    for n in range(1, c // SHIFT_ALIGN + 1):
        if c % n == 0 and (c // n) % SHIFT_ALIGN == 0 and c // n <= _CT_MAX:
            return c // n
    raise ValueError(f"c={c} has no {SHIFT_ALIGN}-aligned lane tile")


def _signs2d(start, sub, key):
    """(sub, 128) ±1 signs for global coordinates [start, start+128·sub)
    in vreg layout — the same murmur stream as CirculantSketch._sign_of.
    ``start`` may be a traced scalar."""
    idx = (start
           + 128 * lax.broadcasted_iota(jnp.int32, (sub, 128), 0)
           + lax.broadcasted_iota(jnp.int32, (sub, 128), 1)).astype(_U32)
    h = _mix32(idx * key + _U32(_GOLDEN))
    # Mosaic can't cast uint32 -> f32 directly; the top bit is 0/1 so an
    # int32 hop is exact
    return 1.0 - 2.0 * (h >> 31).astype(jnp.int32).astype(jnp.float32)


def _signs2d_modc(base, q, c, sub, key):
    """Signs for input coordinates base + ((q + u) mod c), u the flat
    vreg-layout offset — the encode span crosses the block's mod-c seam
    at most once, so one conditional subtract realizes the mod."""
    pos = (q
           + 128 * lax.broadcasted_iota(jnp.int32, (sub, 128), 0)
           + lax.broadcasted_iota(jnp.int32, (sub, 128), 1))
    pos = pos - jnp.where(pos >= c, c, 0)
    h = _mix32((base + pos).astype(_U32) * key + _U32(_GOLDEN))
    return 1.0 - 2.0 * (h >> 31).astype(jnp.int32).astype(jnp.float32)


def _decode_kernel(shifts_ref, keys_ref, t_ref, out_ref, *, c, r, ct):
    b, t = pl.program_id(0), pl.program_id(1)
    sub = ct // 128
    ests = []
    for j in range(r):
        # est[i] = sign(b·c+i) · table[j, (i + s) mod c]: the span starts
        # q = (t·ct + s) mod c into the row; with s 1024-aligned, q//128
        # is a whole vreg offset and the wrap padding makes the slice
        # contiguous — no rotate
        q = (t * ct + shifts_ref[j, b]) % c
        span = t_ref[j, pl.ds(q // 128, sub)]            # (sub, 128)
        ests.append(_signs2d(b * c + t * ct, sub, keys_ref[j]) * span)
    out_ref[0, 0] = median_axis0(jnp.stack(ests, axis=0))


def _encode_kernel(shifts_ref, keys_ref, v_ref, out_ref, *, c, r, ct):
    t, b = pl.program_id(0), pl.program_id(1)
    sub = ct // 128

    @pl.when(b == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    for j in range(r):
        # table[j, t·ct + u] += sign(input) · v_b[(t·ct + u − s) mod c]
        q = (t * ct + c - shifts_ref[j, b]) % c
        span = v_ref[0, pl.ds(q // 128, sub)]            # (sub, 128)
        out_ref[0, j] += _signs2d_modc(b * c, q, c, sub,
                                       keys_ref[j]) * span


def _wrap_pad(x3, sub):
    """(..., n, 128) -> (..., n+sub, 128) with the first ``sub``
    sublane-rows appended, so a mod-n span never wraps."""
    return jnp.concatenate([x3, x3[..., :sub, :]], axis=-2)


@functools.partial(jax.jit, static_argnames=("c", "r", "m", "interpret"))
def pallas_encode(vec_padded, shifts, sign_keys, *, c, r, m,
                  interpret=False):
    """(m*c,) padded fp32 vector -> (r, c) table. ``shifts``: (r, m) int32
    multiples of SHIFT_ALIGN; ``sign_keys``: (r,) uint32."""
    ct = _lane_tile(c)
    sub, csub, nct = ct // 128, c // 128, c // ct
    blocks = _wrap_pad(
        vec_padded.astype(jnp.float32).reshape(m, csub, 128), sub)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        # lane-tiles outer, vector blocks inner: each inner step streams
        # one whole wrap-padded block (ONE DMA) and accumulates all r
        # rows of the resident (1, r, sub, 128) table tile
        grid=(nct, m),
        in_specs=[pl.BlockSpec((1, csub + sub, 128),
                               lambda t, b, *_: (b, 0, 0))],
        out_specs=pl.BlockSpec((1, r, sub, 128),
                               lambda t, b, *_: (t, 0, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_encode_kernel, c=c, r=r, ct=ct),
        out_shape=jax.ShapeDtypeStruct((nct, r, sub, 128), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(shifts, sign_keys, blocks)
    # (nct, r, sub, 128) -> (r, c): element (t, j, s, l) is
    # table[j, t·ct + s·128 + l]
    return out.transpose(1, 0, 2, 3).reshape(r, c)


@functools.partial(jax.jit, static_argnames=("c", "r", "m", "interpret"))
def pallas_decode(table, shifts, sign_keys, *, c, r, m, interpret=False):
    """(r, c) table -> (m*c,) per-coordinate median estimates."""
    ct = _lane_tile(c)
    sub, csub, nct = ct // 128, c // 128, c // ct
    t3 = _wrap_pad(table.astype(jnp.float32).reshape(r, csub, 128), sub)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(m, nct),
        # constant index map: the whole wrap-padded table loads into VMEM
        # once and stays resident for all m·nct steps
        in_specs=[pl.BlockSpec((r, csub + sub, 128),
                               lambda b, t, *_: (0, 0, 0))],
        out_specs=pl.BlockSpec((1, 1, sub, 128),
                               lambda b, t, *_: (b, t, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, c=c, r=r, ct=ct),
        out_shape=jax.ShapeDtypeStruct((m, nct, sub, 128), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(shifts, sign_keys, t3)
    return out.reshape(-1)
