"""Flatten model parameter pytrees to the single contiguous fp32 vector the
framework operates on, and back.

Reference equivalent: CommEfficient/utils.py:261-297 (`get_param_vec` /
`set_param_vec` / `get_grad_vec`), which loop over ``model.parameters()`` and
``torch.cat`` the pieces. In JAX the canonical tool is
``jax.flatten_util.ravel_pytree``; the unravel closure it returns is traceable,
so flatten/unflatten happen *inside* the jitted round step with no host trips
(the reference pays a host↔device copy per round, fed_worker.py:41).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def ravel_params(params: Any) -> Tuple[jax.Array, Callable[[jax.Array], Any]]:
    """Return (flat fp32 vector, unravel closure)."""
    flat, unravel = ravel_pytree(params)
    return flat.astype(jnp.float32), unravel


def make_unraveler(params: Any) -> Tuple[int, Callable[[jax.Array], Any]]:
    """Return (grad_size, unravel closure) for a parameter pytree."""
    flat, unravel = ravel_params(params)
    return int(flat.size), unravel
