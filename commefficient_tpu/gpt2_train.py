"""GPT-2 DoubleHeads on federated PersonaChat.

Parity target: reference CommEfficient/gpt2_train.py (365 LoC) — tokenizer +
DoubleHeads model with 5 added special tokens, plain SGD(lr=1) wrapped in the
federated optimizer ("HAVE TO USE SGD FOR FED", gpt2_train.py:287), linear
LR decay to zero (302-307), the same epoch/round loop as the CV driver, and
final perplexity/accuracy evaluation (test_gpt2, 149).

Run:  python -m commefficient_tpu.gpt2_train --mode sketch \
          --error_type virtual --num_workers 4 --local_batch_size -1 ...
"""

from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import (FedConfig,
                                      enable_compilation_cache, parse_args)
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.cv_train import (
    build_mesh,
    run_validation,
    setup_checkpointing,
    train as shared_train,
)
from commefficient_tpu.data.fed_persona import FedPERSONA, get_tokenizer
from commefficient_tpu.losses import make_gpt2_train_loss, make_gpt2_val_loss
from commefficient_tpu.models.gpt2 import (
    GPT2Config,
    GPT2DoubleHeads,
    load_hf_weights,
)
from commefficient_tpu.utils import TableLogger, Timer


def build_gpt2(cfg: FedConfig, tokenizer):
    n_vocab = len(tokenizer)
    if cfg.do_test:
        gcfg = GPT2Config.small(vocab_size=n_vocab - 5)
    else:
        gcfg = GPT2Config(vocab_size=n_vocab - 5,
                          compute_dtype=jnp.dtype(cfg.compute_dtype),
                          remat=cfg.do_remat)
    return GPT2DoubleHeads(gcfg), gcfg


def main(argv=None):
    cfg = parse_args(argv, default_lr=0.16)  # reference gpt2 lr lineage
    enable_compilation_cache(cfg)
    np.random.seed(cfg.seed)
    if cfg.do_test:
        cfg = cfg.replace(num_cols=10, num_rows=1, k=10)
    cfg = cfg.replace(dataset_name="PERSONA")

    timer = Timer()
    tokenizer = get_tokenizer(cfg.model_checkpoint)
    max_seq_len = 64 if cfg.do_test else 280
    train_ds = FedPERSONA(cfg.dataset_dir, train=True, do_iid=cfg.do_iid,
                          num_clients=cfg.num_clients, tokenizer=tokenizer,
                          num_candidates=cfg.num_candidates,
                          max_seq_len=max_seq_len,
                          max_history=cfg.max_history,
                          personality_permutations=cfg.personality_permutations)
    # same prep config as train (a differing config would invalidate the
    # shared npz cache); permutations only augment the TRAIN pack
    val_ds = FedPERSONA(cfg.dataset_dir, train=False, tokenizer=tokenizer,
                        num_candidates=cfg.num_candidates,
                        max_seq_len=max_seq_len,
                        max_history=cfg.max_history,
                        personality_permutations=cfg.personality_permutations)
    cfg = cfg.replace(num_clients=train_ds.num_clients)

    model, gcfg = build_gpt2(cfg, tokenizer)
    sample = train_ds.gather(np.zeros((1,), np.int64))
    params = model.init(jax.random.PRNGKey(cfg.seed),
                        jnp.asarray(sample["input_ids"]),
                        jnp.asarray(sample["mc_token_ids"]),
                        jnp.asarray(sample["token_type_ids"]))
    loaded = load_hf_weights(params, gcfg, cfg.model_checkpoint)
    if loaded is not None:
        params = loaded
        print("loaded pretrained GPT-2 weights")
    else:
        print("WARNING: no local pretrained GPT-2; training from scratch")

    loss_train = make_gpt2_train_loss(model, cfg.lm_coef, cfg.mc_coef)
    loss_val = make_gpt2_val_loss(model)
    runtime = FedRuntime(cfg, params, loss_train, loss_val,
                         num_clients=train_ds.num_clients,
                         mesh=build_mesh(cfg))
    state = runtime.init_state()
    print(f"grad size {runtime.cfg.grad_size}; "
          f"initialized in {timer():.2f}s")

    ckpt_mgr, start_epoch, restored = setup_checkpointing(
        cfg, runtime, "gpt2_doubleheads")
    if restored is not None:
        state = restored

    state, summary = shared_train(cfg, runtime, state, train_ds, val_ds,
                                  loggers=(TableLogger(),), timer=timer,
                                  ckpt_mgr=ckpt_mgr,
                                  start_epoch=start_epoch)

    if summary is not None:
        nll = summary["test_loss"]
        print(f"final val nll {nll:.4f} ppl {math.exp(min(nll, 20)):.2f} "
              f"mc acc {summary['test_acc']:.4f}")
    if cfg.do_checkpoint and summary is not None:
        os.makedirs(cfg.checkpoint_path, exist_ok=True)
        path = os.path.join(cfg.checkpoint_path, "gpt2_doubleheads.npz")
        np.savez(path, ps_weights=np.asarray(runtime.flat_weights(state)))
        print(f"saved checkpoint to {path}")
    return summary


if __name__ == "__main__":
    main()
