"""GPT-2 DoubleHeads on federated PersonaChat.

Parity target: reference CommEfficient/gpt2_train.py (365 LoC) — tokenizer +
DoubleHeads model with 5 added special tokens, plain SGD(lr=1) wrapped in the
federated optimizer ("HAVE TO USE SGD FOR FED", gpt2_train.py:287), linear
LR decay to zero (302-307), the same epoch/round loop as the CV driver, and
final perplexity/accuracy evaluation (test_gpt2, 149).

Run:  python -m commefficient_tpu.gpt2_train --mode sketch \
          --error_type virtual --num_workers 4 --local_batch_size -1 ...
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import (FedConfig,
                                      enable_compilation_cache, parse_args)
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.cv_train import (
    build_mesh,
    run_validation,
    setup_checkpointing,
    train as shared_train,
)
from commefficient_tpu.data.fed_persona import FedPERSONA, get_tokenizer
from commefficient_tpu.losses import make_gpt2_train_loss, make_gpt2_val_loss
from commefficient_tpu.models.gpt2 import (
    GPT2Config,
    GPT2DoubleHeads,
    gpt2_model_flops,
    load_hf_weights,
    resolve_attn,
)
from commefficient_tpu.utils import TableLogger, TSVLogger, Timer


# batch leaf -> index of its sequence dimension in the per-round arrays
# (leaves mapped to None replicate over the seq axis); leaf shapes are
# (W, B, num_candidates, S) for token arrays
PERSONA_SEQ_SPEC = {"input_ids": 3, "token_type_ids": 3, "lm_labels": 3,
                    "mc_token_ids": None, "mc_label": None}


def build_gpt2(cfg: FedConfig, tokenizer):
    n_vocab = len(tokenizer)
    if cfg.do_test:
        gcfg = GPT2Config.small(vocab_size=n_vocab - 5,
                                remat=cfg.do_remat,
                                remat_policy=cfg.remat_policy)
    else:
        gcfg = GPT2Config(vocab_size=n_vocab - 5,
                          compute_dtype=jnp.dtype(cfg.compute_dtype),
                          remat=cfg.do_remat,
                          remat_policy=cfg.remat_policy)
    return GPT2DoubleHeads(gcfg, attn_impl=resolve_attn(cfg.attn_impl)), gcfg


def make_gpt2_schedule(cfg: FedConfig):
    """Reference GPT-2 LR trajectory: LINEAR lr -> 0 from step 0
    (gpt2_train.py:302-307) — not the CV triangular ramp. ``--lr_warmup``
    (TPU-native opt-in; the reference has no GPT-2 warmup) prepends a
    linear 0 -> lr ramp peaking at ``--pivot_epoch``, giving GPT-2 the CV
    driver's triangular shape — a stabilizer arm of the round-5 sketch
    study (from-scratch GPT-2 under plain SGD diverges unclipped;
    warmup is the classical alternative to clipping)."""
    from commefficient_tpu.utils import PiecewiseLinear
    lr0 = cfg.lr_scale if cfg.lr_scale is not None else 0.16
    if cfg.lr_warmup:
        pivot = min(float(cfg.pivot_epoch), float(cfg.num_epochs))
        return PiecewiseLinear([0.0, pivot, float(cfg.num_epochs)],
                               [0.0, lr0, 0.0])
    return PiecewiseLinear([0.0, float(cfg.num_epochs)], [lr0, 0.0])


def save_pretrained(out_dir: str, runtime, state, gcfg: GPT2Config,
                    tokenizer) -> None:
    """Reference parity for ``model.save_pretrained(log_dir)`` +
    ``tokenizer.save_pretrained`` + config (fed_aggregator.py:208-211,
    gpt2_train.py:146, 280-283): the saved directory is reloadable as a
    pretrained checkpoint WITHOUT the writing run's code/config in hand —
    weights + model config + tokenizer artifacts together."""
    os.makedirs(out_dir, exist_ok=True)
    from commefficient_tpu.checkpoint import params_fingerprint
    # fingerprint needs only treedef + leaf shapes: eval_shape avoids
    # materializing the full pytree (hundreds of MB at real GPT-2 scale)
    params_shape = jax.eval_shape(runtime.unravel,
                                  runtime.flat_weights(state))
    np.savez(os.path.join(out_dir, "weights.npz"),
             ps_weights=np.asarray(runtime.flat_weights(state)))
    cfg_dict = dataclasses.asdict(gcfg)
    cfg_dict["compute_dtype"] = str(jnp.dtype(gcfg.compute_dtype))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({"model_type": "gpt2_doubleheads", **cfg_dict,
                   "params_fingerprint": params_fingerprint(params_shape)},
                  f, indent=1)
    if hasattr(tokenizer, "save_pretrained"):      # real GPT-2 BPE
        tokenizer.save_pretrained(out_dir)
    else:                                          # offline HashTokenizer
        with open(os.path.join(out_dir, "hash_tokenizer.json"), "w") as f:
            json.dump({"type": "HashTokenizer",
                       "base_vocab": tokenizer.base_vocab}, f)
    print(f"saved pretrained checkpoint to {out_dir}")


def load_pretrained(out_dir: str):
    """Rebuild (model, params, gcfg, tokenizer) from a ``save_pretrained``
    directory. Refuses weight vectors whose layout does not match the
    rebuilt model (fingerprint check)."""
    from commefficient_tpu.checkpoint import params_fingerprint
    from commefficient_tpu.data.fed_persona import HashTokenizer
    with open(os.path.join(out_dir, "config.json")) as f:
        cfg_dict = json.load(f)
    saved_fp = cfg_dict.pop("params_fingerprint", None)
    cfg_dict.pop("model_type", None)
    cfg_dict["compute_dtype"] = jnp.dtype(cfg_dict["compute_dtype"])
    gcfg = GPT2Config(**cfg_dict)
    model = GPT2DoubleHeads(gcfg)
    ids = jnp.zeros((1, 2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids,
                        jnp.zeros((1, 2), jnp.int32), ids)
    fp = params_fingerprint(params)
    if saved_fp is not None and fp != saved_fp:
        raise ValueError(
            f"{out_dir}: saved weights were written under a different "
            f"parameter layout ({saved_fp} != {fp})")
    from commefficient_tpu.ops import ravel_params
    _, unravel = ravel_params(params)
    flat = np.load(os.path.join(out_dir, "weights.npz"))["ps_weights"]
    params = unravel(jnp.asarray(flat))
    hash_fn = os.path.join(out_dir, "hash_tokenizer.json")
    if os.path.exists(hash_fn):
        with open(hash_fn) as f:
            tokenizer = HashTokenizer(json.load(f)["base_vocab"])
    else:
        tokenizer = get_tokenizer(out_dir)
    return model, params, gcfg, tokenizer


def main(argv=None):
    cfg = parse_args(argv, default_lr=0.16)  # reference gpt2 lr lineage
    enable_compilation_cache(cfg)
    np.random.seed(cfg.seed)
    if cfg.do_test:
        cfg = cfg.replace(num_cols=10, num_rows=1, k=10)
    cfg = cfg.replace(dataset_name="PERSONA")

    timer = Timer()
    tokenizer = get_tokenizer(cfg.model_checkpoint)
    max_seq_len = cfg.max_seq_len or (64 if cfg.do_test else 280)
    train_ds = FedPERSONA(cfg.dataset_dir, train=True, do_iid=cfg.do_iid,
                          num_clients=cfg.num_clients, tokenizer=tokenizer,
                          num_candidates=cfg.num_candidates,
                          max_seq_len=max_seq_len,
                          max_history=cfg.max_history,
                          personality_permutations=cfg.personality_permutations)
    # same prep config as train (a differing config would invalidate the
    # shared npz cache); permutations only augment the TRAIN pack
    val_ds = FedPERSONA(cfg.dataset_dir, train=False, tokenizer=tokenizer,
                        num_candidates=cfg.num_candidates,
                        max_seq_len=max_seq_len,
                        max_history=cfg.max_history,
                        personality_permutations=cfg.personality_permutations)
    cfg = cfg.replace(num_clients=train_ds.num_clients)

    model, gcfg = build_gpt2(cfg, tokenizer)
    sample = train_ds.gather(np.zeros((1,), np.int64))
    params = model.init(jax.random.PRNGKey(cfg.seed),
                        jnp.asarray(sample["input_ids"]),
                        jnp.asarray(sample["mc_token_ids"]),
                        jnp.asarray(sample["token_type_ids"]))
    loaded = load_hf_weights(params, gcfg, cfg.model_checkpoint)
    if loaded is not None:
        params = loaded
        print("loaded pretrained GPT-2 weights")
    else:
        print("WARNING: no local pretrained GPT-2; training from scratch")

    # long-context configuration: --mesh_axes clients,seq runs every
    # client's model with the sequence sharded over the "seq" axis (ring
    # attention, parallel/ring.py) — per-device attention memory drops from
    # O(S^2) to O(S^2/n_seq) and activations to O(S/n_seq). New scope
    # beyond the reference (SURVEY.md §5: no sequence parallelism).
    mesh = build_mesh(cfg)
    seq_shards = (mesh.shape["seq"]
                  if mesh is not None and "seq" in mesh.axis_names else 1)
    if seq_shards > 1:
        if max_seq_len % seq_shards:
            raise ValueError(
                f"the seq mesh axis size ({seq_shards}) must divide "
                f"max_seq_len ({max_seq_len})")
        train_model = GPT2DoubleHeads(gcfg, seq_axis="seq",
                                      seq_shards=seq_shards)
        # lm_chunk is passed so the unsupported lm_chunk+seq combination
        # FAILS FAST in the loss builder instead of silently running dense
        loss_train = make_gpt2_train_loss(train_model, cfg.lm_coef,
                                          cfg.mc_coef, seq_axis="seq",
                                          seq_shards=seq_shards,
                                          lm_chunk=cfg.lm_chunk)
        print(f"sequence parallelism: ring attention over {seq_shards} "
              "shards")
    else:
        loss_train = make_gpt2_train_loss(model, cfg.lm_coef, cfg.mc_coef,
                                          lm_chunk=cfg.lm_chunk)
    # validation always runs the dense model (same param pytree); on a
    # mesh the val batch shards over all devices (runtime._val_step_sharded)
    loss_val = make_gpt2_val_loss(model, lm_chunk=cfg.lm_chunk)
    runtime = FedRuntime(cfg, params, loss_train, loss_val,
                         num_clients=train_ds.num_clients,
                         mesh=mesh,
                         seq_spec=(PERSONA_SEQ_SPEC if seq_shards > 1
                                   else None))
    state = runtime.init_state()
    print(f"grad size {runtime.cfg.grad_size}; "
          f"initialized in {timer():.2f}s")

    ckpt_mgr, start_epoch, restored, resume_info = setup_checkpointing(
        cfg, runtime, "gpt2_doubleheads")
    if restored is not None:
        state = restored

    from commefficient_tpu.cv_train import make_writer
    from commefficient_tpu.telemetry import maybe_create as make_telemetry
    from commefficient_tpu.utils import make_logdir
    # one logdir shared by telemetry + tensorboard (see cv_train.main);
    # --logdir pins it so a resumed run appends to its predecessor's
    # stream with a `resume` lineage record
    logdir = (cfg.logdir or make_logdir(cfg)
              if cfg.telemetry or cfg.use_tensorboard else None)
    # resolved config (grad_size, auto-sized num_cols) for the manifest
    telemetry = make_telemetry(
        runtime.cfg, "gpt2_train", logdir=logdir,
        resume_info=(None if resume_info is None else {
            "round": resume_info["global_round"],
            "epoch": start_epoch,
            "checkpoint": resume_info["checkpoint"]}))
    if telemetry is not None:
        telemetry.instrument(runtime)
        telemetry.memory_event("init")
    # analytic MFU numerator for the utilization telemetry: the scanned
    # round makes XLA's cost analysis under-count ~10x (models/gpt2.py
    # gpt2_model_flops); tokens/round = W x B x candidates x seq
    round_tokens = (cfg.num_workers * runtime.batch_size
                    * cfg.num_candidates * max_seq_len)
    round_flops = gpt2_model_flops(gcfg, round_tokens, max_seq_len)
    tsv = TSVLogger()
    try:
        state, summary = shared_train(cfg, runtime, state, train_ds, val_ds,
                                      loggers=(TableLogger(), tsv),
                                      timer=timer, ckpt_mgr=ckpt_mgr,
                                      start_epoch=start_epoch,
                                      schedule=make_gpt2_schedule(cfg),
                                      writer=make_writer(cfg, logdir=logdir),
                                      telemetry=telemetry,
                                      model_flops_per_round=round_flops,
                                      resume_info=resume_info)
    finally:
        if telemetry is not None:
            telemetry.close()
    print(tsv)

    if summary is not None:
        nll = summary["test_loss"]
        print(f"final val nll {nll:.4f} ppl {math.exp(min(nll, 20)):.2f} "
              f"mc acc {summary['test_acc']:.4f}")
    if cfg.do_checkpoint and summary is not None:
        # reference parity: weights + config + tokenizer, reloadable
        # without this run's code in hand (fed_aggregator.py:208-211)
        save_pretrained(os.path.join(cfg.checkpoint_path,
                                     "gpt2_doubleheads"),
                        runtime, state, gcfg, tokenizer)
    return summary


if __name__ == "__main__":
    main()
