"""HLO collective ledger: count, kind and byte size of every cross-device
collective in a compiled executable.

Round 5's post-mortem (VERDICT weak #2) is the reason this exists: the
per-client-row home<->compute layout conversion silently unrolled into 32
separate 492-element all_to_alls per round, and nothing noticed — the
multichip dryrun asserted collective *size* only, so a pathology that
multiplies collective *count* (32 launches of pure latency per round at
GPT-2 scale) regressed invisibly. The ledger walks the compiled HLO text
(``lowered.compile().as_text()`` — the same artifact
``__graft_entry__._collective_report`` already parses for sizes) and
records every all-reduce / reduce-scatter / all-gather / all-to-all /
collective-permute with its element count, dtype and byte size, so both
the telemetry stream (``collectives`` events, emitted by the JitWatcher
on every compile) and the dryruns (hard count assertions) see the same
inventory.

Parsing notes, measured against the XLA versions in this image:
- async scheduling splits ops into ``-start``/``-done`` pairs; only the
  ``-start`` (or the sync form) is counted, never both.
- combined collectives have tuple result types (``(f32[3,64], f32[])``);
  each tuple element is one ledger entry (they travel as one launch but
  the payload accounting wants every element). ``combined_in``
  back-references the launch index so count-of-launches stays exact.
- ``/*index=N*/`` comments inside >5-element tuple types are stripped
  before matching (their ``=`` breaks the op match).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "all-to-all", "collective-permute")

# Per-round LAUNCH-count ceilings for EVERY collective kind, asserted
# by __graft_entry__.dryrun_multichip (all 5 modes) and
# scripts/multihost_dryrun.py — one dict so the two dryruns and the
# tests can never drift apart. Measured on the current toolchain:
# local_topk runs the intended 4 tiled all_to_alls (vel+err x
# home->compute and back), every mode stays <= 10 all-reduces, 1
# reduce-scatter, <= 23 all-gathers, and the sketch round's top-k /
# signal machinery peaks at 293 collective-permutes. The bounds add
# slack for scheduler variation; the round-5 regression class (a layout
# conversion unrolling into per-ROW launches, VERDICT weak #2) scales
# with the row/shard count and blows through whichever kind it hits by
# ~an order of magnitude — bounding only the aggregation kinds would
# leave a gather/permute unroll invisible, the exact blind spot this
# ledger exists to close.
ROUND_COLLECTIVE_LAUNCH_BOUNDS = {
    "all-to-all": 4,
    "reduce-scatter": 2,
    "all-reduce": 12,
    "all-gather": 32,
    "collective-permute": 384,
}

# dtype -> bytes per element, for the dtypes XLA spells in result types
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_OP_RE = re.compile(
    r"=\s+(\(?[^=]*?)\s*"
    r"(all-reduce|reduce-scatter|all-gather|all-to-all|collective-permute)"
    r"(-start)?\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def ledger_from_hlo(hlo_text: str) -> List[Dict[str, Any]]:
    """One entry per collective result element:
    ``{kind, n_elements, dtype, bytes, combined_in}``.

    ``combined_in`` is the 0-based index of the LAUNCH the entry belongs
    to — entries sharing it came from one combined (tuple-result)
    collective, so ``len({e['combined_in']})`` is the true launch count
    while ``len(entries)`` counts payload elements.
    """
    entries: List[Dict[str, Any]] = []
    launch = 0
    for line in hlo_text.splitlines():
        # strip /*index=N*/ comments: XLA annotates tuple types beyond 5
        # elements with them, and their '=' breaks the op match
        line = re.sub(r"/\*.*?\*/", "", line)
        m = _OP_RE.search(line)
        if not m:
            continue
        if m.group(3) and "-done(" in line:
            continue  # defensive; -done never matches the -start group
        result_type, kind = m.group(1), m.group(2)
        found = False
        for dtype, dims_s in _SHAPE_RE.findall(result_type):
            dims = [int(x) for x in dims_s.split(",") if x]
            n = 1
            for d in dims:
                n *= d
            nbytes = n * _DTYPE_BYTES.get(dtype, 4)
            entries.append({"kind": kind, "n_elements": n, "dtype": dtype,
                            "bytes": nbytes, "combined_in": launch})
            found = True
        if found:
            launch += 1
    return entries


def ledger_from_compiled(compiled) -> List[Dict[str, Any]]:
    """Ledger of a ``lowered.compile()`` result. Best-effort: an
    executable that cannot render its HLO yields an empty ledger rather
    than an exception (observability never kills the run)."""
    try:
        return ledger_from_hlo(compiled.as_text())
    except Exception:
        return []


def collective_wire_bytes(entry: Dict[str, Any],
                          n_devices: int) -> float:
    """Modeled per-device ICI bytes of one collective under ring
    algorithms — what actually crosses the wire, as opposed to the
    entry's RESULT bytes (a reduce-scatter's result is 1/n of its
    input, so raw result bytes would under-count it n-fold against an
    all_to_all of the same payload):

    - all-reduce: 2 * bytes * (n-1)/n (reduce-scatter + all-gather);
    - reduce-scatter: input = n * result, each device sends
      (n-1)/n of it -> result_bytes * (n-1);
    - all-gather / all-to-all: each device sends (n-1)/n of the
      (result-sized) payload;
    - collective-permute: the whole payload moves once.

    n == 1 is zero: a single-device "collective" crosses no wire.
    """
    n = max(int(n_devices), 1)
    if n == 1:
        return 0.0
    b = float(entry["bytes"])
    kind = entry["kind"]
    if kind == "all-reduce":
        return 2.0 * b * (n - 1) / n
    if kind == "reduce-scatter":
        return b * (n - 1)
    if kind in ("all-gather", "all-to-all"):
        return b * (n - 1) / n
    return b


def table_reduce_wire_bytes(entries: List[Dict[str, Any]],
                            n_devices: int) -> float:
    """Per-device ICI bytes of the round's table-REDUCE collectives:
    the reduce-scattered f32/bf16 table, or the int8 column-shard +
    f32-scale all_to_alls that replace it under ``--wire_dtype int8``
    (ops/wire.py). In the sketch round these two kinds ARE the table
    reduce — the rows_cols all_to_alls exist only for dense-mode client
    rows — so filtering by kind needs no size heuristics. This is the
    quantity ISSUE-14's dryrun gate bounds (int8 <= 0.30x f32) and
    ``teleview diff --wire_bytes_growth`` regresses."""
    return sum(collective_wire_bytes(e, n_devices) for e in entries
               if e["kind"] in ("reduce-scatter", "all-to-all"))


def summarize_ledger(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a ledger into the ``collectives`` telemetry event body:
    per-kind launch counts, total payload bytes, and the raw ops list."""
    counts: Dict[str, int] = {}
    launches_seen: Dict[str, set] = {}
    total_bytes = 0
    for e in entries:
        launches_seen.setdefault(e["kind"], set()).add(e["combined_in"])
        total_bytes += e["bytes"]
    for kind, launches in launches_seen.items():
        counts[kind] = len(launches)
    return {
        "n_collectives": sum(counts.values()),
        "counts": counts,
        "total_bytes": total_bytes,
        "ops": entries,
    }


def round_ledger(runtime, state, client_ids, batch, mask, lr=0.1):
    """Lower+compile the runtime's round step on the given arguments and
    return its collective ledger — the dryrun/test entry point (the
    telemetry path instead hooks the JitWatcher's compile)."""
    import jax.numpy as jnp
    lowered = runtime._round.lower(
        state, client_ids, batch, mask,
        jnp.asarray(lr, jnp.float32), runtime.cs,
        getattr(runtime, "_gid", None))
    return ledger_from_compiled(lowered.compile())
