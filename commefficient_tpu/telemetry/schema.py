"""The run-telemetry JSONL event schema, and its validator.

One ``telemetry.jsonl`` line = one JSON object = one event. Every event
carries the envelope fields ``event`` (type tag), ``t`` (unix seconds)
and ``seq`` (0-based per-run counter, so a truncated stream is
detectable). The first line of a well-formed stream is a ``manifest``
and the last is a ``summary`` — the footer's absence marks a run that
died rather than finished.

The validator is dependency-free (no jsonschema package in the image):
each event type maps its required fields to a type predicate; extra
fields are always legal (forward compatibility), unknown event types
are not. ``scripts/check_telemetry_schema.py`` and the tier-1 tests
both run exactly this code, so the schema documented in README.md is
the one actually enforced.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Tuple

SCHEMA_VERSION = 11
# streams written by older code stay readable: v1 lacks the span /
# utilization event types (added in v2), v2 lacks client_stats / alert
# (added in v3), v3 lacks async_round (added in v4), v4 lacks defense
# (added in v5), v5 lacks memory_ledger and the enriched memory /
# utilization fields (added in v6 — the first version to ADD FIELDS to
# existing event types; see FIELDS_SINCE_V6, which the validator only
# requires of v6+ streams), v6 lacks the utilization mesh-topology
# fields (n_devices / mesh_shape, added in v7 for the scaling-curve
# harness — FIELDS_SINCE_V7, same vintage-gated requirement), v7 lacks
# the fault/resume event types and the manifest stream_id (added in v8
# for crash recovery lineage — FIELDS_SINCE_V8), v8 lacks the quantized-
# wire fields on collectives/signals/bench (wire_dtype and the modeled
# table-reduce ICI bytes, added in v9 for --wire_dtype int8 —
# FIELDS_SINCE_V9), v9 lacks the layer_signals event type (the
# layer-wise compression attribution stream, added in v10 — a new type,
# no vintage-gated field additions), v10 lacks the population event
# type and the client_stats `estimated` flag (population-scale sketch
# observability, added in v11 — FIELDS_SINCE_V11), but each is
# otherwise a subset of its successor — so the validator accepts any
# supported manifest version. A version it does not know is the error,
# not a version merely older than current.
SUPPORTED_SCHEMA_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10,
                             SCHEMA_VERSION)
TELEMETRY_BASENAME = "telemetry.jsonl"


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _opt_num(v: Any) -> bool:
    return v is None or _num(v)


def _int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _str(v: Any) -> bool:
    return isinstance(v, str)


def _bool(v: Any) -> bool:
    return isinstance(v, bool)


def _opt_str(v: Any) -> bool:
    return v is None or isinstance(v, str)


def _dict(v: Any) -> bool:
    return isinstance(v, dict)


def _opt_dict(v: Any) -> bool:
    return v is None or isinstance(v, dict)


def _list(v: Any) -> bool:
    return isinstance(v, list)


def _opt_list(v: Any) -> bool:
    return v is None or isinstance(v, list)


# event type -> {required field: predicate}. The envelope (event/t/seq)
# is checked for every line before the per-type fields.
EVENT_FIELDS: Dict[str, Dict[str, Any]] = {
    # run header: resolved config + environment, written once at open
    "manifest": {
        "schema": _int,
        "run_type": _str,          # cv_train | gpt2_train | bench | ...
        "jax_version": _str,
        "backend": _str,
        "device_kind": _str,
        "device_count": _int,
        "mesh_shape": _list,
        "mesh_axes": _list,
        "grad_size": _int,
        "sketch": _opt_dict,       # geometry dict in sketch mode, else null
        "config": _dict,           # full resolved FedConfig
        # schema v8: unique id of this stream SEGMENT — a resumed run
        # appends a new manifest with a fresh id, and its `resume`
        # event names the predecessor's (crash-recovery lineage)
        "stream_id": _str,
    },
    # one federated round (emitted every cfg.telemetry_every rounds).
    # loss/acc are null when the round's metrics went non-finite — the
    # writer serializes NaN/inf as null so the stream stays strict JSON
    "round": {
        "round": _int,
        "epoch": _int,
        "lr": _num,
        "loss": _opt_num,
        "acc": _opt_num,
        "n_valid": _num,
        "download_bytes": _opt_num,   # null when --no_track_bytes
        "upload_bytes": _opt_num,
        "host_s": _num,               # host batch assembly
        "dispatch_s": _num,           # jitted-call return (async dispatch)
        "device_s": _num,             # block_until_ready remainder
    },
    # per-epoch validation record (mirrors the console table row);
    # loss/acc metrics are null if non-finite (e.g. a NaN val sweep that
    # does not trip the train-side divergence abort)
    "epoch": {
        "epoch": _int,
        "lr": _num,
        "train_time": _num,
        "train_loss": _opt_num,
        "train_acc": _opt_num,
        "test_loss": _opt_num,
        "test_acc": _opt_num,
        "download_mib": _num,
        "upload_mib": _num,
        "total_time": _num,
    },
    # one XLA compile of a watched jitted function; n_compiles > 1 for a
    # name means a RECOMPILE (shape change / donation miss) happened
    "compile": {
        "name": _str,
        "n_compiles": _int,
        "lower_s": _num,
        "compile_s": _num,
        "flops": _opt_num,            # XLA cost_analysis; null if opaque
        "bytes_accessed": _opt_num,
        "fallback": _bool,            # True: watcher gave up on AOT path
    },
    # per-device memory_stats() snapshot (+ host RSS). Schema v6 adds
    # the derived residency fields (telemetry/memory_ledger.py
    # residency_fields): max-over-devices live/peak bytes, the peak's
    # growth since the PREVIOUS snapshot (which phase grew the
    # high-water), fragmentation = peak - live, the device byte limit
    # and the headroom fraction (limit - peak)/limit — the near-OOM
    # precursor health.py's hbm_pressure rule watches. All null on
    # backends without allocator stats (CPU) — never fake zeros.
    "memory": {
        "phase": _str,                # init | rounds_<n> | epoch_<n> | ...
        "devices": _list,             # [{id, kind, stats: dict|null}, ...]
        "host_rss_bytes": _opt_num,
        "live_bytes": _opt_num,
        "peak_bytes": _opt_num,
        "delta_peak_bytes": _opt_num,
        "fragmentation_bytes": _opt_num,
        "limit_bytes": _opt_num,
        "headroom_frac": _opt_num,
    },
    # static byte inventory of one compiled executable (schema v6,
    # telemetry/memory_ledger.py, from XLA's memory_analysis): temp
    # buffers (the working set — where a fusion regression or the
    # sketch round's dense-gradient materialization shows up),
    # argument/output/alias bytes (the resident state the executable
    # touches) and generated-code bytes. Emitted by the JitWatcher next
    # to each `compile` event; dryrun_multichip asserts hard ceilings.
    # Fields are null when XLA reported no count — never fake zeros.
    "memory_ledger": {
        "name": _str,                 # watched function (round_step, ...)
        "temp_bytes": _opt_num,
        "argument_bytes": _opt_num,
        "output_bytes": _opt_num,
        "alias_bytes": _opt_num,
        "generated_code_bytes": _opt_num,
        "total_bytes": _opt_num,      # arg + output + temp + generated
    },
    # structured divergence diagnostic, emitted instead of a bare exit
    "nan_abort": {
        "nan_round": _int,            # -1: host-side NaN (epoch loss)
        "reason": _str,
        "mode": _str,
        "max_grad_norm": _opt_num,
        "sketch": _opt_dict,
        "last_round": _opt_dict,      # last finite round record, if any
        "last_epoch": _opt_dict,      # last completed epoch record, if any
    },
    # benchmark stage result (bench.py / bench_gpt2.py share the stream)
    # schema v9 adds wire_dtype so BENCH trajectory arms under different
    # --wire_dtype settings stay distinguishable from the stream alone
    "bench": {
        "metric": _str,
        "result": _dict,
        "wire_dtype": _opt_str,
    },
    # compression-signal health for one round (telemetry/signals.py):
    # on-device norms of the aggregated gradient / EF accumulators /
    # applied update, sketch collision-noise proxies, heavy-hitter
    # recovery overlap, and exact per-client byte costs. Norm fields are
    # null when not applicable to the mode/topology (e.g. no dense
    # pre-image on a mesh) — never silently zero
    "signals": {
        "round": _int,
        "mode": _str,
        "grad_norm": _opt_num,
        "grad_true_norm": _opt_num,     # dense preimage norm, if one exists
        "grad_l2estimate": _opt_num,    # sketch table norm estimate
        "velocity_norm": _opt_num,
        "error_norm": _opt_num,
        "error_l2estimate": _opt_num,
        "update_norm": _opt_num,
        "support_density": _opt_num,
        "topk_overlap": _opt_num,       # --signals_exact only, else null
        "download_bytes": _opt_num,     # round totals; null w/o track_bytes
        "upload_bytes": _opt_num,
        "client_download_bytes": _opt_list,  # per participating client,
        "client_upload_bytes": _opt_list,    # ordered by client_ids
        "wire_dtype": _opt_str,              # v9: the table wire dtype
    },
    # layer-wise compression attribution for one round (schema v10,
    # telemetry/layer_signals.py): per-parameter-group reductions of
    # the round's dense quantities, one list entry per named group in
    # ravel order. Masses are squared-L2 energies (additive — per-group
    # masses sum to the matching whole-vector signal norm squared);
    # topk_count sums to nnz(update) (= k for the sparsifying modes).
    # grad_mass/error_mass/hh_overlap are null — never fake zeros —
    # where the round holds no dense gradient / dense EF / exact
    # reference (fused-encode and mesh sketch rounds; --signals_exact
    # off), mirroring the signals NaN contract. Entries inside live
    # lists may be null too (a group that owns no top-k winner has no
    # defined hh_overlap).
    "layer_signals": {
        "round": _int,
        "mode": _str,
        "signal_groups": _str,          # coarse | leaf (the config axis)
        "groups": _list,                # group names, ravel order
        "sizes": _list,                 # coordinate counts per group
        "grad_mass": _opt_list,
        "update_mass": _opt_list,
        "topk_count": _opt_list,
        "error_mass": _opt_list,
        "hh_overlap": _opt_list,
    },
    # collective inventory of one compiled executable (telemetry/
    # collectives.py): per-kind LAUNCH counts, total payload bytes and
    # the per-element op list — emitted next to each `compile` event so
    # a collective-count regression (the round-5 32x all_to_all unroll
    # class) is visible in every run's stream, not only in the dryruns
    "collectives": {
        "name": _str,                   # watched function (round_step, ...)
        "n_collectives": _int,          # total launches
        "counts": _dict,                # kind -> launch count
        "total_bytes": _num,
        "ops": _list,                   # [{kind, n_elements, dtype, bytes,
                                        #   combined_in}, ...]
        # schema v9 (--wire_dtype int8): the configured table wire dtype
        # and the MODELED per-device ICI bytes of the table-reduce
        # collectives (reduce-scatter / all-to-all; collectives.py
        # table_reduce_wire_bytes) — the quantized-wire regression
        # channel `teleview diff --wire_bytes_growth` gates
        "wire_dtype": _opt_str,
        "table_reduce_bytes": _opt_num,
    },
    # batched wall-time spans (telemetry/tracing.py): the tracer's
    # completed-span buffer, drained at the round-record cadence OUTSIDE
    # the timed region. Each span: {name, ts (seconds since t0 on the
    # monotonic clock), dur_s, tid, depth}. t0_wall anchors the
    # monotonic epoch to unix time; teleview's `timeline` subcommand
    # renders the stream into a perfetto/chrome-tracing trace.json
    "span": {
        "t0_wall": _num,
        "n_dropped": _int,            # spans lost to the buffer cap in
                                      # THIS window (per-event counts sum
                                      # to the run total)
        "spans": _list,
    },
    # step-time attribution + MFU (telemetry/utilization.py): per-round
    # device time joined with the compiled round's cost-analysis FLOPs
    # and the per-device_kind peak table (--peak_flops overrides).
    # flops_per_round/mfu are null when no FLOPs count or no peak is
    # known — never a fake zero; the three *_frac fields are fractions
    # of wall_s and need not sum to 1 (device waits are only measured
    # on rounds that synced)
    # schema v6 adds the roofline attribution fields (utilization.py
    # roofline_fields): cost-analysis bytes-accessed joined with the
    # FLOPs into arithmetic intensity, the ridge point of the pinned
    # peak pair, a compute/bandwidth bound verdict, achieved-vs-peak
    # bandwidth fraction and the two-term expected round time. Null
    # whenever a byte count or a peak is unknown — never fake zeros.
    "utilization": {
        "round": _int,
        "rounds": _int,               # rounds in this window
        "wall_s": _num,
        "device_kind": _str,
        "peak_flops": _opt_num,
        "flops_per_round": _opt_num,
        "flops_source": _opt_str,     # cost_analysis | analytic | null
        "achieved_flops": _opt_num,   # FLOP/s over the window
        "mfu": _opt_num,
        "input_wait_frac": _opt_num,  # host batch assembly (starvation)
        "dispatch_frac": _opt_num,
        "device_wait_frac": _opt_num,
        "straggler_spread": _opt_num,  # (max-min)/mean per-host device_s
        "peak_hbm_gbps": _opt_num,    # GB/s (--peak_hbm_gbps overrides)
        "bytes_per_round": _opt_num,  # cost-analysis bytes accessed
        "bytes_source": _opt_str,     # cost_analysis | null
        "arithmetic_intensity": _opt_num,  # FLOPs per byte accessed
        "ridge_intensity": _opt_num,  # peak_flops / peak_hbm bytes/s
        "bound": _opt_str,            # compute | bandwidth | null
        "achieved_gbps": _opt_num,    # bytes * rounds / wall_s, in GB/s
        "bw_frac": _opt_num,          # achieved_gbps / peak_hbm_gbps
        "expected_round_s": _opt_num,  # max(flops/peakF, bytes/peakBW)
        # schema v7 (the scaling-curve harness): the window's mesh
        # topology, so per-chip normalization (throughput/chip, the
        # weak-scaling contract) is computable from the stream alone.
        # n_devices is the device count the watched executable ran
        # over; mesh_shape the mesh dims (null when no mesh)
        "n_devices": _opt_num,
        "mesh_shape": _opt_list,
    },
    # per-client population summary for one round (telemetry/clients.py):
    # on-device quantile reductions over the round's client axis (the
    # full (W,) vectors never reach the stream — JSONL stays small at
    # num_workers=512) joined with the host-side participation ledger.
    # ``quantiles`` maps each stat key (loss, grad_norm_pre/post,
    # clip_frac, tx_norm, upload/download_bytes) to
    # {p5,p25,p50,p75,p95,max,mean,argmax_client}; values are null where
    # the stat does not exist for the mode/path (e.g. per-client grad
    # norms under the fused-clients fast path) — never silently zero
    "client_stats": {
        "round": _int,
        "n_participants": _int,       # client slots in this round
        "quantiles": _dict,
        "coverage": _num,             # distinct participants / num_clients
        "distinct_clients": _int,     # seen at least once so far
        "counts_p50": _opt_num,       # per-seen-client sample counts
        "counts_max": _opt_num,
        "staleness_p50": _opt_num,    # rounds since last participation
        "staleness_max": _opt_num,
        # schema v11: whether the participation fields are sketch
        # estimates (--population_sketch; telemetry/population.py) —
        # the ledger never fakes exactness
        "estimated": _bool,
    },
    # population-scale participation summary (schema v11, telemetry/
    # population.py + the exact ledger's population_snapshot): the
    # ledger's full view of the client universe at the record cadence.
    # In sketch mode (estimated=true) distinct/coverage come from a KMV
    # bottom-S estimator, counts/staleness quantiles from its uniform
    # distinct-client sample (DKW rank bound), counts via a count-min
    # sketch whose (epsilon, delta) ride along, and the top_* lists are
    # space-saving top-K over the most-sampled / loss-argmax /
    # quarantine-strike streams ([id, count] pairs, count an upper
    # estimate). obs_count/gap quantiles are P2 estimates of the
    # per-participation sample-count and staleness-at-participation
    # streams in BOTH modes; sketch parameters are null in exact mode —
    # never fake values
    "population": {
        "round": _int,
        "estimated": _bool,
        "registered": _int,           # configured client universe size
        "distinct": _num,             # distinct-participant (estimate)
        "coverage": _num,
        "counts_p50": _opt_num,       # per-seen-client cumulative counts
        "counts_p95": _opt_num,
        "counts_max": _opt_num,
        "staleness_p50": _opt_num,    # rounds since last participation
        "staleness_p95": _opt_num,
        "staleness_max": _opt_num,
        "obs_count_p50": _opt_num,    # per-participation sample counts
        "obs_count_p95": _opt_num,
        "gap_p50": _opt_num,          # staleness at participation
        "gap_p95": _opt_num,
        "top_sampled": _list,         # [[client_id, count], ...] desc
        "top_loss": _list,
        "top_strikes": _list,
        "memory_bytes": _num,         # ledger resident footprint model
        "cm_epsilon": _opt_num,       # count-min e/width; null if exact
        "cm_delta": _opt_num,         # count-min e^-depth; null if exact
        "hh_k": _opt_num,             # space-saving capacity; null if exact
        "sample_size": _opt_num,      # KMV sample size; null if exact
    },
    # one async buffered-aggregation commit (core/async_agg.py): which
    # cohorts merged, their measured staleness (commits between dispatch
    # and merge) and discount weights, the raw datum count the commit
    # averaged over, and the post-commit EF-accumulator norms —
    # the staleness-divergence signal health.py's async_ef_blowup rule
    # watches. ``round`` is the COMMIT index (the server version), not a
    # dispatch tick; ``partial`` marks the epoch-boundary flush of a
    # buffer below --buffer_goal. loss is the datum-weighted dispatch
    # loss of the merged cohorts; the device-derived fields (loss,
    # buffer_n, *_norm) are null off the record cadence — fetching them
    # costs a host sync, and a null is never a fake zero
    "async_round": {
        "round": _int,
        "n_cohorts": _int,
        "cohorts": _list,             # global round index of each cohort
        "staleness_mean": _num,
        "staleness_max": _num,
        "discount_mean": _num,
        "discount_min": _num,
        "partial": _bool,
        "buffer_n": _opt_num,
        "loss": _opt_num,
        "update_norm": _opt_num,
        "error_norm": _opt_num,
        "velocity_norm": _opt_num,
        "lr": _num,
    },
    # robustness status of one round (schema v5; core/runtime.py +
    # core/quarantine.py): what the configured defense actually did —
    # clip fraction/threshold/removed mass (normclip), trim fraction
    # (trim), per-round nonfinite-client count and the quarantine
    # ledger's bench/eject state — plus the injected adversary counts
    # when fault injection is on. Emitted only when the robustness
    # subsystem is active (defense, adversary or quarantine configured);
    # numeric fields are null where not applicable to the configured
    # defense/action — never silently zero
    "defense": {
        "round": _int,
        "defense": _str,              # none | normclip | trim
        "adversary": _str,            # none | labelflip | ... (config)
        "nonfinite_action": _str,     # abort | quarantine
        "clip_frac": _opt_num,        # clipped / participating clients
        "clip_thresh": _opt_num,      # per-datum norm threshold applied
        "clipped_mass": _opt_num,     # L2 of the mass the clip removed
        "trim_frac": _opt_num,        # 2*floor(trim_frac*V)/V actually
                                      # cut, V = live (data-carrying)
                                      # clients, not the slot count W
        "nonfinite_clients": _opt_num,  # zeroed out of THIS round
        "quarantined": _int,          # currently benched (backoff running)
        "ejected": _int,              # permanently ejected so far
        "quarantine_ids_digest": _opt_str,  # "<n>:<sha1[:12]>" or null
        "injected": _opt_dict,        # {kind: slots-this-round} when on
    },
    # a run-level fault (schema v8, core/preempt.py + the drivers):
    # what interrupted or degraded the run, and what survived it. kind:
    # "preempt" = graceful SIGTERM/SIGINT drain (signal + grace used +
    # the preempt-tagged checkpoint written); "corrupt_checkpoint" = a
    # resume fell back past a damaged generation (detail names it);
    # "round_stall" = the hang watchdog's deadline expired;
    # "fetch_retry" = a retryable input phase needed a backoff retry.
    # round is -1 when no round context exists (a fault at resume
    # time). Numeric/str fields are null where not applicable.
    "fault": {
        "round": _int,
        "kind": _str,             # preempt | corrupt_checkpoint |
                                  # round_stall | fetch_retry | kill
        "signal": _opt_str,       # SIGTERM | SIGINT | null
        "grace_s": _opt_num,      # drain seconds actually used
        "detail": _opt_str,       # human context (paths, errors)
        "checkpoint": _opt_str,   # checkpoint written/skipped, if any
    },
    # crash-recovery lineage (schema v8): a resumed run's first records.
    # Written when the stream is opened in APPEND mode over a
    # predecessor's events.jsonl (prior_stream/prior_events name the
    # segment it continues) and/or when the driver restores a
    # checkpoint (round/epoch/checkpoint say where training resumes;
    # round is -1 when only the stream — not training state — resumed).
    "resume": {
        "round": _int,            # first global round of the resumed run
        "epoch": _opt_num,
        "checkpoint": _opt_str,   # the generation restored from
        "prior_stream": _opt_str,  # predecessor segment's stream_id
        "prior_events": _opt_num,  # events the predecessor had written
    },
    # online anomaly alert (telemetry/health.py): a monitor rule fired
    # against the rolling median/MAD history of a watched stream field.
    # zscore/median/mad are null for non-statistical rules (nonfinite
    # precursors); ``action`` records the configured --alert_action so
    # postmortems know whether a flight-recorder bundle should exist
    "alert": {
        "round": _int,
        "rule": _str,
        "severity": _str,             # info | warn | critical
        "metric": _str,
        "value": _opt_num,
        "zscore": _opt_num,
        "median": _opt_num,
        "mad": _opt_num,
        "window": _int,
        "action": _str,               # log | warn | checkpoint | abort
    },
    # end-of-run footer
    "summary": {
        "run_type": _str,
        "aborted": _bool,
        "n_rounds": _int,
        "total_download_mib": _opt_num,
        "total_upload_mib": _opt_num,
        "wall_time_s": _num,
        "event_counts": _dict,
        "final": _opt_dict,           # last epoch record / bench result
    },
}

ENVELOPE = {"event": _str, "t": _num, "seq": _int}

# fields ADDED to pre-existing event types in schema v6 (the residency
# and roofline enrichments): a v1-v5 stream legitimately omits them, so
# the validator only REQUIRES them of v6+ streams — but a pre-v6 stream
# that does carry one must still type-check (forward-written fields are
# ordinary extra fields otherwise).
FIELDS_SINCE_V6: Dict[str, Tuple[str, ...]] = {
    "memory": ("live_bytes", "peak_bytes", "delta_peak_bytes",
               "fragmentation_bytes", "limit_bytes", "headroom_frac"),
    "utilization": ("peak_hbm_gbps", "bytes_per_round", "bytes_source",
                    "arithmetic_intensity", "ridge_intensity", "bound",
                    "achieved_gbps", "bw_frac", "expected_round_s"),
}

# fields ADDED in schema v7 (the scaling-curve mesh-topology fields) —
# same vintage-gated requirement as FIELDS_SINCE_V6
FIELDS_SINCE_V7: Dict[str, Tuple[str, ...]] = {
    "utilization": ("n_devices", "mesh_shape"),
}

# fields ADDED in schema v8 (crash-recovery lineage) — same vintage-
# gated requirement: pre-v8 manifests legitimately carry no stream_id
FIELDS_SINCE_V8: Dict[str, Tuple[str, ...]] = {
    "manifest": ("stream_id",),
}

# fields ADDED in schema v9 (the quantized sketch wire, --wire_dtype
# int8) — same vintage-gated requirement
FIELDS_SINCE_V9: Dict[str, Tuple[str, ...]] = {
    "collectives": ("wire_dtype", "table_reduce_bytes"),
    "signals": ("wire_dtype",),
    "bench": ("wire_dtype",),
}

# fields ADDED in schema v11 (population-scale sketch observability:
# the participation fields may now be estimates, and the flag says so)
# — same vintage-gated requirement
FIELDS_SINCE_V11: Dict[str, Tuple[str, ...]] = {
    "client_stats": ("estimated",),
}


def validate_event(obj: Any,
                   version: int = SCHEMA_VERSION) -> List[str]:
    """Return a list of problems with one decoded event (empty = valid).
    ``version`` is the stream's manifest schema version: fields added in
    a later version than the stream claims are optional for it (see
    FIELDS_SINCE_V6) — validate_lines threads the observed manifest
    version through; standalone calls default to the current schema."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"event is not an object: {type(obj).__name__}"]
    for field, pred in ENVELOPE.items():
        if field not in obj:
            problems.append(f"missing envelope field {field!r}")
        elif not pred(obj[field]):
            problems.append(f"envelope field {field!r} has wrong type")
    kind = obj.get("event")
    if not isinstance(kind, str):
        return problems
    spec = EVENT_FIELDS.get(kind)
    if spec is None:
        problems.append(f"unknown event type {kind!r}")
        return problems
    v6_only = FIELDS_SINCE_V6.get(kind, ())
    v7_only = FIELDS_SINCE_V7.get(kind, ())
    v8_only = FIELDS_SINCE_V8.get(kind, ())
    v9_only = FIELDS_SINCE_V9.get(kind, ())
    v11_only = FIELDS_SINCE_V11.get(kind, ())
    for field, pred in spec.items():
        if field not in obj:
            if version < 6 and field in v6_only:
                continue
            if version < 7 and field in v7_only:
                continue
            if version < 8 and field in v8_only:
                continue
            if version < 9 and field in v9_only:
                continue
            if version < 11 and field in v11_only:
                continue
            problems.append(f"{kind}: missing field {field!r}")
        elif not pred(obj[field]):
            problems.append(
                f"{kind}: field {field!r} fails its type check "
                f"(got {type(obj[field]).__name__})")
    return problems


def validate_lines(lines: Iterable[str]) -> List[Tuple[int, str]]:
    """Validate an iterable of JSONL lines. Returns [(lineno, problem)];
    also checks the stream shape: seq must be 0,1,2,..., the first event
    must be a manifest with a SUPPORTED schema version."""
    problems: List[Tuple[int, str]] = []
    expected_seq = 0
    version = SCHEMA_VERSION
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            problems.append((lineno, f"not valid JSON: {e}"))
            continue
        if (isinstance(obj, dict) and obj.get("event") == "manifest"
                and obj.get("schema") in SUPPORTED_SCHEMA_VERSIONS):
            # the stream's own vintage governs which per-event fields
            # are required of it (see validate_event / FIELDS_SINCE_V6)
            version = obj["schema"]
        for p in validate_event(obj, version=version):
            problems.append((lineno, p))
        if isinstance(obj, dict):
            if expected_seq == 0 and obj.get("event") != "manifest":
                problems.append((lineno, "first event must be a manifest"))
            if (obj.get("event") == "manifest"
                    and obj.get("schema") not in SUPPORTED_SCHEMA_VERSIONS):
                problems.append(
                    (lineno, f"manifest schema {obj.get('schema')!r} not in "
                             f"supported {SUPPORTED_SCHEMA_VERSIONS}"))
            if obj.get("seq") != expected_seq:
                problems.append(
                    (lineno, f"seq {obj.get('seq')!r} != expected "
                             f"{expected_seq} (truncated/merged stream?)"))
            if isinstance(obj.get("seq"), int):
                # resynchronize to the observed counter: one gap is one
                # problem, not a cascade of bogus mismatches on every
                # following line
                expected_seq = obj["seq"] + 1
            else:
                expected_seq += 1
        # non-object lines (already flagged above) do not advance the
        # counter: the writer's own seq continues around an insertion
    if expected_seq == 0:
        problems.append((0, "empty stream (no events)"))
    return problems


def validate_file(path: str) -> List[Tuple[int, str]]:
    with open(path) as f:
        return validate_lines(f)
