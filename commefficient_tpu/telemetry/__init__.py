"""Run-telemetry subsystem: structured per-round metrics, compile and
memory observability, compression-signal health (signals.py), the HLO
collective ledger (collectives.py), wall-time span tracing (tracing.py),
MFU/starvation accounting (utilization.py) and profiler window management —
shared by ``cv_train.py``, ``gpt2_train.py``, ``bench.py`` and
``bench_gpt2.py``. See schema.py for the JSONL event schema and
README.md ("Telemetry & profiling") for the consumer-facing contract;
``scripts/teleview.py`` summarizes and diffs the streams offline."""

from commefficient_tpu.telemetry.clients import (CLIENT_STAT_KEYS,
                                                 ParticipationLedger,
                                                 client_stats_to_host,
                                                 quantiles_ordered,
                                                 summarize_per_client)
from commefficient_tpu.telemetry.collectives import (ledger_from_compiled,
                                                     ledger_from_hlo,
                                                     round_ledger,
                                                     summarize_ledger)
from commefficient_tpu.telemetry.health import (MONITORED_KINDS,
                                                AnomalyMonitor,
                                                FlightRecorder,
                                                robust_z)
from commefficient_tpu.telemetry.compilewatch import JitWatcher
from commefficient_tpu.telemetry.memory_ledger import (MEMORY_KEYS,
                                                       MEMORY_LEDGER_KEYS,
                                                       ResidencyTracker,
                                                       check_ceilings,
                                                       check_dense_grad_floor,
                                                       ledger_from_compiled,
                                                       ledger_from_stats,
                                                       residency_fields,
                                                       round_memory_ceilings,
                                                       round_memory_ledger)
from commefficient_tpu.telemetry.profiling import (ProfilerWindow,
                                                   parse_profile_rounds)
from commefficient_tpu.telemetry.run import RunTelemetry, maybe_create
from commefficient_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                TELEMETRY_BASENAME,
                                                validate_event,
                                                validate_file,
                                                validate_lines)
from commefficient_tpu.telemetry.layer_signals import (LAYER_SIGNAL_KEYS,
                                                       GroupSpec,
                                                       layer_group_signals,
                                                       layer_signals_to_host,
                                                       make_group_spec,
                                                       starved_groups)
from commefficient_tpu.telemetry.signals import (SIGNAL_KEYS, round_signals,
                                                 signals_to_host)
from commefficient_tpu.telemetry.tracing import (NullTracer, SpanTracer,
                                                 span)
from commefficient_tpu.telemetry.utilization import (PEAK_FLOPS_BY_KIND,
                                                     PEAK_HBM_GBPS_BY_KIND,
                                                     ROOFLINE_KEYS,
                                                     UtilizationTracker,
                                                     emit_from_totals,
                                                     peak_flops_for,
                                                     peak_hbm_for,
                                                     roofline_fields)

__all__ = [
    "CLIENT_STAT_KEYS",
    "ParticipationLedger",
    "client_stats_to_host",
    "quantiles_ordered",
    "summarize_per_client",
    "MONITORED_KINDS",
    "AnomalyMonitor",
    "FlightRecorder",
    "robust_z",
    "JitWatcher",
    "ProfilerWindow",
    "parse_profile_rounds",
    "RunTelemetry",
    "maybe_create",
    "SCHEMA_VERSION",
    "TELEMETRY_BASENAME",
    "validate_event",
    "validate_file",
    "validate_lines",
    "SIGNAL_KEYS",
    "round_signals",
    "signals_to_host",
    "LAYER_SIGNAL_KEYS",
    "GroupSpec",
    "layer_group_signals",
    "layer_signals_to_host",
    "make_group_spec",
    "starved_groups",
    "ledger_from_hlo",
    "ledger_from_compiled",
    "round_ledger",
    "summarize_ledger",
    "NullTracer",
    "SpanTracer",
    "span",
    "PEAK_FLOPS_BY_KIND",
    "PEAK_HBM_GBPS_BY_KIND",
    "ROOFLINE_KEYS",
    "UtilizationTracker",
    "emit_from_totals",
    "peak_flops_for",
    "peak_hbm_for",
    "roofline_fields",
    "MEMORY_KEYS",
    "MEMORY_LEDGER_KEYS",
    "ResidencyTracker",
    "check_ceilings",
    "check_dense_grad_floor",
    "ledger_from_compiled",
    "ledger_from_stats",
    "residency_fields",
    "round_memory_ceilings",
    "round_memory_ledger",
]
