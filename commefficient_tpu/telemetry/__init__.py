"""Run-telemetry subsystem: structured per-round metrics, compile and
memory observability, and profiler window management — shared by
``cv_train.py``, ``gpt2_train.py``, ``bench.py`` and ``bench_gpt2.py``.
See schema.py for the JSONL event schema and README.md ("Telemetry &
profiling") for the consumer-facing contract."""

from commefficient_tpu.telemetry.compilewatch import JitWatcher
from commefficient_tpu.telemetry.profiling import (ProfilerWindow,
                                                   parse_profile_rounds)
from commefficient_tpu.telemetry.run import RunTelemetry, maybe_create
from commefficient_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                TELEMETRY_BASENAME,
                                                validate_event,
                                                validate_file,
                                                validate_lines)

__all__ = [
    "JitWatcher",
    "ProfilerWindow",
    "parse_profile_rounds",
    "RunTelemetry",
    "maybe_create",
    "SCHEMA_VERSION",
    "TELEMETRY_BASENAME",
    "validate_event",
    "validate_file",
    "validate_lines",
]
