"""Lightweight span tracer: where does a round's wall time actually go?

The ``round`` telemetry event carries a whole-round host/dispatch/device
split, but nothing below that granularity — when the host phase grows,
nothing says whether the data gather, the sampler, or the JSONL flush
grew. ``span("data_fetch")`` / ``span("dispatch")`` / ``span("device_wait")``
context managers mark the phases that own wall time; completed spans
buffer in memory (two ``perf_counter`` calls + one list append each) and
are drained into batched ``span`` telemetry events at the round-record
cadence, which ``scripts/teleview.py timeline`` renders into a
perfetto/chrome-tracing ``trace.json``.

Dependency-free on purpose (``threading`` + ``time`` only): the data
layer (``data/fed_dataset.py``) and the offline tooling must be able to
reason about spans without jax in the room.

Zero overhead when telemetry is off: the module-level :func:`span`
delegates to a process-global tracer that defaults to a
:class:`NullTracer`, whose ``span()`` returns one shared no-op context
manager — no allocation, no clock reads, no lock. The drivers
:func:`install` a real :class:`SpanTracer` only when a telemetry stream
exists, and :func:`uninstall` it on the way out.

Thread-safety: spans may open/close on any thread (nesting depth is
tracked per thread); the completed-span buffer is lock-protected, and
``drain()`` swaps the buffer atomically.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


class _NullSpan:
    """Shared no-op context manager — the entire cost of a span when
    tracing is off is one attribute lookup and one call returning this
    singleton."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class NullTracer:
    """The installed-by-default tracer: spans are no-ops, drains are
    empty. Keeps every instrumentation site unconditional — no
    ``if telemetry`` branches in the hot paths."""

    enabled = False
    t0_wall = 0.0
    dropped = 0

    def span(self, name: str) -> _NullSpan:
        return NULL_SPAN

    def drain(self) -> List[Dict[str, Any]]:
        return []

    def pop_dropped(self) -> int:
        return 0


class _Span:
    """One live span (context manager). Records on exit only — an
    exception inside the span still produces the span, with the time it
    actually took."""

    __slots__ = ("_tracer", "_name", "_t0", "_depth")

    def __init__(self, tracer: "SpanTracer", name: str):
        self._tracer = tracer
        self._name = name

    def __enter__(self) -> "_Span":
        self._depth = self._tracer._enter_depth()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        self._tracer._record(self._name, self._t0, t1 - self._t0,
                             self._depth)
        return False


class SpanTracer:
    """Buffers completed spans for periodic drain into the telemetry
    stream.

    Spans carry ``ts`` (seconds since the tracer's epoch, measured on
    the monotonic ``perf_counter`` clock — NTP steps cannot reorder
    them), ``dur_s``, ``tid`` (a small per-tracer thread ordinal) and
    ``depth`` (nesting level within the thread). ``t0_wall`` anchors the
    monotonic epoch to unix time once, so offline tools can align spans
    with the events' absolute ``t`` fields.

    ``max_spans`` bounds the buffer: a run that never drains (telemetry
    record cadence 0) drops further spans and counts them in
    ``dropped`` instead of growing without limit. ``pop_dropped()``
    returns-and-resets that counter, so each ``span`` event reports the
    drops of ITS window — per-event counts sum to the true total.
    """

    enabled = True

    def __init__(self, max_spans: int = 100_000):
        self.t0_wall = time.time()
        self.t0 = time.perf_counter()
        self.max_spans = max_spans
        self.dropped = 0
        self._buf: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        self._tids: Dict[int, int] = {}

    # ------------------------------------------------------------- recording

    def span(self, name: str) -> _Span:
        return _Span(self, name)

    def _enter_depth(self) -> int:
        d = getattr(self._local, "depth", 0)
        self._local.depth = d + 1
        return d

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _record(self, name: str, t0: float, dur: float, depth: int) -> None:
        self._local.depth = depth  # restore: this span closed
        rec = {"name": name, "ts": round(t0 - self.t0, 6),
               "dur_s": round(dur, 6), "tid": self._tid(), "depth": depth}
        with self._lock:
            if len(self._buf) >= self.max_spans:
                self.dropped += 1
                return
            self._buf.append(rec)

    # --------------------------------------------------------------- reading

    def drain(self) -> List[Dict[str, Any]]:
        """Return and clear the completed-span buffer (open spans land in
        a later drain)."""
        with self._lock:
            out, self._buf = self._buf, []
            return out

    def pop_dropped(self) -> int:
        """Drops since the last pop (atomically reset)."""
        with self._lock:
            d, self.dropped = self.dropped, 0
            return d


# process-global tracer: instrumentation sites call tracing.span(name)
# unconditionally; only a driver that owns a telemetry stream installs a
# recording tracer.
_TRACER: Any = NullTracer()


def current():
    return _TRACER


def install(tracer: Optional[SpanTracer] = None) -> SpanTracer:
    """Make ``tracer`` (or a fresh SpanTracer) the process-global tracer;
    returns it. Pair with :func:`uninstall` in a finally block."""
    global _TRACER
    if tracer is None:
        tracer = SpanTracer()
    _TRACER = tracer
    return tracer


def uninstall() -> None:
    global _TRACER
    _TRACER = NullTracer()


def span(name: str):
    """Open a span on the current tracer (a shared no-op when tracing is
    off). Usage: ``with tracing.span("data_fetch"): ...``"""
    return _TRACER.span(name)
