"""Population-scale participation observability: bounded-memory
streaming summaries of a client universe too large to ledger exactly.

ROADMAP item 2 scales the client POPULATION (>=10^6 registered
clients), and the exact :class:`~commefficient_tpu.telemetry.clients.
ParticipationLedger` — a per-client host dict — is the first thing that
breaks there: its memory, its observe loop and its checkpoint sidecar
all grow O(population). This module applies FetchSGD's own move to the
telemetry plane: the population stream is summarized by fixed-size,
seed-keyed sketches instead of held exactly.

:class:`PopulationLedger` keeps the exact ledger's interface
(``observe`` / ``snapshot`` / ``state_dict`` / ``load_state_dict``) and
backs it with four summaries:

- **Count-min sketch** (:class:`CountMinSketch`) over per-client
  cumulative sample counts. With depth ``d`` and width ``w`` the
  estimate for any client overestimates its true count by at most
  ``epsilon * N`` (N = total observed weight) with probability at least
  ``1 - delta``, where ``epsilon = e / w`` and ``delta = e ** -d``
  (Cormode & Muthukrishnan). Defaults d=4, w=65536: epsilon ~= 4.15e-5,
  delta ~= 1.8e-2, table 2 MiB.
- **Space-saving top-K** (:class:`SpaceSaving`) over three keyed
  streams — most-sampled clients, per-round loss-argmax winners (the
  client_stats argmax channel) and quarantine-strike ids. Any item
  whose true weight exceeds ``N / K`` is guaranteed present, and every
  reported count overestimates truth by at most its stored error bound
  (<= min-count <= N/K) (Metwally et al.).
- **P² streaming quantiles** (:class:`P2Quantile`) over the two
  insertion-only per-participation streams: the per-slot sample count
  and the staleness-at-participation gap (rounds since the same client
  last participated). O(1) memory per tracked quantile.
- **KMV distinct sample** (:class:`KMVSample`): the S smallest hashes
  over distinct client ids. Yields the distinct-participant estimate
  ``(S-1)/U_(S)`` (relative error ~ 1/sqrt(S); S=4096 -> ~1.6%) AND a
  uniform sample of distinct clients carrying their EXACT cumulative
  sample count and last-participation round — a client whose hash ranks
  in the bottom S now ranked there at every earlier time, so its stats
  have been tracked since its first appearance. Quantiles over the
  sample estimate the population quantiles with DKW rank error
  ``sqrt(ln(2/delta_q) / (2*S))`` (~1.9% rank at delta_q=2e-13... at
  delta_q=0.01 it is ~1.8e-2); snapshot quantile checks in the dryrun
  gate use this bound.

Memory budget (defaults), independent of population size::

    count-min table   d*w*8            = 2.00 MiB
    space-saving x3   3 * K*(3*8B + ~120B dict/heap overhead)  ~ 0.11 MiB
    KMV sample        S*(3*8B + ~180B dict/heap overhead)      ~ 0.80 MiB
    P2 markers        4 quantiles * O(1)                       ~ 0 MiB
    total                                                      < 3 MiB

— documented ceiling 8 MiB (``MEMORY_BUDGET_BYTES``), asserted by the
``dryrun_multichip`` population gate at 10^6 registered clients.

Everything is deterministic: hashing is seed-keyed splitmix64, batch
processing visits unique ids in ascending order, evictions tie-break on
id — so ``state_dict`` after a kill-at-N/2 resume is BITWISE identical
to an uninterrupted run's (the preemption contract of core/preempt.py).
This module imports numpy only — never jax — so the jitted round's HLO
is invariant to the ledger by construction (identity-gated anyway).
"""

from __future__ import annotations

import base64
import heapq
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# documented host-memory ceiling for one PopulationLedger (see module
# docstring for the accounting); the dryrun gate asserts the measured
# footprint at 10^6 registered clients stays under it
MEMORY_BUDGET_BYTES = 8 * 1024 * 1024

# registered-population threshold at which --population_sketch auto
# switches from the exact ledger to the sketch ledger
AUTO_SKETCH_THRESHOLD = 100_000

_U64 = np.uint64
_MASK64 = _U64(0xFFFFFFFFFFFFFFFF)


def _b64(a: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(a).tobytes()).decode()


def _unb64(s: str, dtype, shape=None) -> np.ndarray:
    a = np.frombuffer(base64.b64decode(s), dtype=dtype).copy()
    return a.reshape(shape) if shape is not None else a


def mix64(ids, seed: int) -> np.ndarray:
    """Seed-keyed splitmix64 finalizer over an int array -> uint64.

    The same counter-based construction ops/wire.py uses for rounding
    noise, host-side: statistically uniform, keyed so two ledgers with
    different seeds disagree, and bit-reproducible across platforms
    (pure uint64 wraparound arithmetic)."""
    with np.errstate(over="ignore"):
        z = (np.asarray(ids, np.uint64)
             + _U64(seed & 0xFFFFFFFFFFFFFFFF)
             * _U64(0x9E3779B97F4A7C15))
        z = (z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)
        return z ^ (z >> _U64(31))


def _aggregate(client_ids, samples_per_slot) -> Tuple[np.ndarray, np.ndarray]:
    """Unique-aggregate one round's (ids, counts) into ascending unique
    ids and their summed positive weights (zero-sample slots dropped —
    they did not participate; see ParticipationLedger.observe)."""
    ids = np.asarray(client_ids).reshape(-1).astype(np.int64)
    counts = (np.asarray(samples_per_slot, np.float64).reshape(-1)
              if samples_per_slot is not None
              else np.ones(ids.shape[0], np.float64))
    keep = counts > 0
    ids, counts = ids[keep], counts[keep]
    if ids.size == 0:
        return ids, counts
    uniq, inv = np.unique(ids, return_inverse=True)
    sums = np.bincount(inv, weights=counts, minlength=uniq.size)
    return uniq, sums


class CountMinSketch:
    """Seed-keyed count-min over int ids, float64 counters.

    Overestimates only: ``query(c) >= true(c)`` always, and
    ``query(c) <= true(c) + epsilon * N`` with probability >= 1 - delta
    (epsilon = e/width, delta = e^-depth, N = total added weight)."""

    def __init__(self, depth: int = 4, width: int = 65536, seed: int = 0):
        if width & (width - 1):
            raise ValueError(f"count-min width must be a power of two, "
                             f"got {width}")
        self.depth, self.width, self.seed = int(depth), int(width), int(seed)
        self.table = np.zeros((self.depth, self.width), np.float64)
        self.total = 0.0

    @property
    def epsilon(self) -> float:
        return math.e / self.width

    @property
    def delta(self) -> float:
        return math.exp(-self.depth)

    def _rows(self, ids: np.ndarray) -> np.ndarray:
        mask = _U64(self.width - 1)
        return np.stack([mix64(ids, self.seed * 1000003 + d + 1) & mask
                         for d in range(self.depth)]).astype(np.int64)

    def add(self, ids, weights) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        w = np.asarray(weights, np.float64).reshape(-1)
        if ids.size == 0:
            return
        for d, row in enumerate(self._rows(ids)):
            np.add.at(self.table[d], row, w)
        self.total += float(w.sum())

    def query(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            return np.zeros(0, np.float64)
        rows = self._rows(ids)
        est = self.table[0][rows[0]]
        for d in range(1, self.depth):
            est = np.minimum(est, self.table[d][rows[d]])
        return est

    @property
    def nbytes(self) -> int:
        return self.table.nbytes

    def state_dict(self) -> Dict[str, Any]:
        return {"depth": self.depth, "width": self.width, "seed": self.seed,
                "total": self.total, "table": _b64(self.table)}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self.depth, self.width = int(d["depth"]), int(d["width"])
        self.seed, self.total = int(d["seed"]), float(d["total"])
        self.table = _unb64(d["table"], np.float64,
                            (self.depth, self.width))


class SpaceSaving:
    """Space-saving top-K heavy hitters (Metwally et al.) over a
    weighted id stream. Deterministic: batches are offered in ascending
    id order and eviction picks the (count, id)-lexicographic minimum.
    ``top()`` reports ``[id, count, err]`` with ``true <= count`` and
    ``count - err <= true`` — err is the eviction floor the id inherited
    (0 for items never evicted), bounded by N/K."""

    def __init__(self, k: int = 256):
        self.k = int(k)
        self._counts: Dict[int, float] = {}
        self._errs: Dict[int, float] = {}
        self.total = 0.0

    def offer(self, ids, weights) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        w = np.asarray(weights, np.float64).reshape(-1)
        if ids.size == 0:
            return
        order = np.argsort(ids, kind="stable")
        ids, w = ids[order], w[order]
        self.total += float(w.sum())
        counts, errs = self._counts, self._errs
        for c, n in zip(ids.tolist(), w.tolist()):
            c = int(c)
            if c in counts:
                counts[c] += n
            elif len(counts) < self.k:
                counts[c] = n
                errs[c] = 0.0
            else:
                # evict the lexicographic (count, id) minimum; the
                # newcomer inherits its count as the error floor
                victim = min(counts, key=lambda i: (counts[i], i))
                floor = counts.pop(victim)
                errs.pop(victim, None)
                counts[c] = floor + n
                errs[c] = floor

    def top(self, n: Optional[int] = None) -> List[List[float]]:
        order = sorted(self._counts, key=lambda i: (-self._counts[i], i))
        if n is not None:
            order = order[:n]
        return [[int(i), float(self._counts[i]), float(self._errs[i])]
                for i in order]

    @property
    def nbytes(self) -> int:
        # 2 dict entries/id: ~(key 28B + float 24B + slot 2*16B) * 2
        return len(self._counts) * 168 + 128

    def state_dict(self) -> Dict[str, Any]:
        ids = np.asarray(sorted(self._counts), np.int64)
        return {"k": self.k, "total": self.total,
                "ids": _b64(ids),
                "counts": _b64(np.asarray(
                    [self._counts[i] for i in ids.tolist()], np.float64)),
                "errs": _b64(np.asarray(
                    [self._errs[i] for i in ids.tolist()], np.float64))}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self.k = int(d["k"])
        self.total = float(d["total"])
        ids = _unb64(d["ids"], np.int64)
        counts = _unb64(d["counts"], np.float64)
        errs = _unb64(d["errs"], np.float64)
        self._counts = {int(i): float(c) for i, c in zip(ids, counts)}
        self._errs = {int(i): float(e) for i, e in zip(ids, errs)}


class P2Quantile:
    """Jain & Chlamtac's P² single-quantile estimator: five markers,
    O(1) memory, no samples stored. Exact until 5 observations."""

    def __init__(self, p: float):
        self.p = float(p)
        self.n = 0
        self._init: List[float] = []       # first five observations
        self._q = [0.0] * 5                # marker heights
        self._pos = [0.0] * 5              # marker positions (1-based)

    def add(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.n <= 5:
            self._init.append(x)
            if self.n == 5:
                self._init.sort()
                self._q = list(self._init)
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
            return
        q, pos, p = self._q, self._pos, self.p
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and x >= q[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            pos[i] += 1.0
        want = [1.0,
                1.0 + (self.n - 1) * p / 2.0,
                1.0 + (self.n - 1) * p,
                1.0 + (self.n - 1) * (1.0 + p) / 2.0,
                float(self.n)]
        for i in (1, 2, 3):
            d = want[i] - pos[i]
            if ((d >= 1.0 and pos[i + 1] - pos[i] > 1.0)
                    or (d <= -1.0 and pos[i - 1] - pos[i] < -1.0)):
                s = 1.0 if d >= 0 else -1.0
                # parabolic (P2) update, clamped to the linear one when
                # it would break marker monotonicity
                qi = q[i] + s / (pos[i + 1] - pos[i - 1]) * (
                    (pos[i] - pos[i - 1] + s) * (q[i + 1] - q[i])
                    / (pos[i + 1] - pos[i])
                    + (pos[i + 1] - pos[i] - s) * (q[i] - q[i - 1])
                    / (pos[i] - pos[i - 1]))
                if not (q[i - 1] < qi < q[i + 1]):
                    j = i + int(s)
                    qi = q[i] + s * (q[j] - q[i]) / (pos[j] - pos[i])
                q[i] = qi
                pos[i] += s

    def value(self) -> Optional[float]:
        if self.n == 0:
            return None
        if self.n < 5:
            s = sorted(self._init)
            return s[min(int(self.p * len(s)), len(s) - 1)]
        return self._q[2]

    def state_dict(self) -> Dict[str, Any]:
        return {"p": self.p, "n": self.n, "init": list(self._init),
                "q": _b64(np.asarray(self._q, np.float64)),
                "pos": _b64(np.asarray(self._pos, np.float64))}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self.p, self.n = float(d["p"]), int(d["n"])
        self._init = [float(x) for x in d["init"]]
        self._q = _unb64(d["q"], np.float64).tolist()
        self._pos = _unb64(d["pos"], np.float64).tolist()


class KMVSample:
    """Bottom-S hashes over distinct client ids: distinct-count
    estimator AND a uniform distinct-client sample with EXACT per-member
    cumulative sample counts and last-participation rounds (membership
    is hash-rank-based, so a current member has been a member — and
    tracked — since its first appearance; evicted ids never return
    because the rank threshold only tightens)."""

    def __init__(self, size: int = 4096, seed: int = 0):
        self.size, self.seed = int(size), int(seed)
        self._hash: Dict[int, int] = {}          # id -> uint64 hash
        self._samples: Dict[int, float] = {}
        self._last: Dict[int, int] = {}
        self._heap: List[Tuple[int, int]] = []   # (-hash, -id): max first

    def observe(self, rnd: int, uniq_ids: np.ndarray,
                weights: np.ndarray) -> List[Tuple[float, float]]:
        """Fold one round's unique-aggregated batch in. Returns the
        (gap, weight) pairs of sampled REPEAT participants — an unbiased
        subsample of the staleness-at-participation stream, in ascending
        id order (the P2 feed)."""
        gaps: List[Tuple[float, float]] = []
        if uniq_ids.size == 0:
            return gaps
        hashes = mix64(uniq_ids, self.seed * 9176 + 77)
        rnd = int(rnd)
        for c, h, n in zip(uniq_ids.tolist(), hashes.tolist(),
                           weights.tolist()):
            c, h = int(c), int(h)
            if c in self._hash:
                gaps.append((float(rnd - self._last[c]), float(n)))
                self._samples[c] += float(n)
                self._last[c] = rnd
                continue
            if len(self._hash) < self.size:
                self._insert(c, h, n, rnd)
                continue
            top_h, top_id = -self._heap[0][0], -self._heap[0][1]
            if (h, c) < (top_h, top_id):
                heapq.heappop(self._heap)
                del self._hash[top_id]
                del self._samples[top_id]
                del self._last[top_id]
                self._insert(c, h, n, rnd)
        return gaps

    def _insert(self, c: int, h: int, n: float, rnd: int) -> None:
        self._hash[c] = h
        self._samples[c] = float(n)
        self._last[c] = rnd
        heapq.heappush(self._heap, (-h, -c))

    def __len__(self) -> int:
        return len(self._hash)

    def distinct(self) -> float:
        """Distinct-id estimate: exact below capacity, else the KMV
        estimator (S-1)/U_(S) with U the max kept hash normalized to
        (0, 1]. Relative error ~ 1/sqrt(S)."""
        if len(self._hash) < self.size:
            return float(len(self._hash))
        u = (-self._heap[0][0] + 1) / 2.0 ** 64
        return (self.size - 1) / u

    def counts(self) -> np.ndarray:
        return np.asarray(sorted(self._samples.values()), np.float64)

    def staleness(self, rnd: int) -> np.ndarray:
        return np.asarray(sorted(int(rnd) - np.fromiter(
            self._last.values(), np.int64)), np.float64)

    @property
    def nbytes(self) -> int:
        # 3 dict entries + 1 heap tuple per id: ~(28+24+16*2)*3 + 72
        return len(self._hash) * 324 + 128

    def state_dict(self) -> Dict[str, Any]:
        # canonical order: ascending (hash, id) — heap layout is an
        # implementation detail and never serialized
        order = sorted(self._hash, key=lambda c: (self._hash[c], c))
        ids = np.asarray(order, np.int64)
        return {"size": self.size, "seed": self.seed,
                "ids": _b64(ids),
                "hashes": _b64(np.asarray(
                    [self._hash[c] for c in order], np.uint64)),
                "samples": _b64(np.asarray(
                    [self._samples[c] for c in order], np.float64)),
                "last": _b64(np.asarray(
                    [self._last[c] for c in order], np.int64))}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self.size, self.seed = int(d["size"]), int(d["seed"])
        ids = _unb64(d["ids"], np.int64)
        hashes = _unb64(d["hashes"], np.uint64)
        samples = _unb64(d["samples"], np.float64)
        last = _unb64(d["last"], np.int64)
        self._hash = {int(c): int(h) for c, h in zip(ids, hashes)}
        self._samples = {int(c): float(n) for c, n in zip(ids, samples)}
        self._last = {int(c): int(r) for c, r in zip(ids, last)}
        self._heap = [(-int(h), -int(c)) for c, h in zip(ids, hashes)]
        heapq.heapify(self._heap)


# the population event's non-envelope fields, in emit order — mirrored
# by the jax-free literal in scripts/teleview.py (pinned by test)
POPULATION_KEYS = (
    "round", "estimated", "registered", "distinct", "coverage",
    "counts_p50", "counts_p95", "counts_max",
    "staleness_p50", "staleness_p95", "staleness_max",
    "obs_count_p50", "obs_count_p95", "gap_p50", "gap_p95",
    "top_sampled", "top_loss", "top_strikes",
    "memory_bytes", "cm_epsilon", "cm_delta", "hh_k", "sample_size",
)


class PopulationLedger:
    """Sketch-backed drop-in for ParticipationLedger (same ``observe`` /
    ``snapshot`` / ``state_dict`` / ``load_state_dict`` interface), host
    memory bounded by :data:`MEMORY_BUDGET_BYTES` independent of the
    population. ``snapshot`` carries ``estimated: True`` — the sketch
    never fakes exactness (the exact ledger's snapshot says False)."""

    estimated = True

    def __init__(self, num_clients: int, *, seed: int = 0,
                 cm_depth: int = 4, cm_width: int = 65536,
                 hh_k: int = 256, sample_size: int = 4096):
        self.num_clients = max(int(num_clients), 1)
        self.seed = int(seed)
        self._cm = CountMinSketch(cm_depth, cm_width, seed=self.seed)
        self._hh_sampled = SpaceSaving(hh_k)
        self._hh_loss = SpaceSaving(hh_k)
        self._hh_strikes = SpaceSaving(hh_k)
        self._kmv = KMVSample(sample_size, seed=self.seed)
        self._p2 = {"obs_count_p50": P2Quantile(0.50),
                    "obs_count_p95": P2Quantile(0.95),
                    "gap_p50": P2Quantile(0.50),
                    "gap_p95": P2Quantile(0.95)}

    # ------------------------------------------------------ ingest
    def observe(self, rnd: int, client_ids, samples_per_slot=None) -> None:
        uniq, sums = _aggregate(client_ids, samples_per_slot)
        if uniq.size == 0:
            return
        self._cm.add(uniq, sums)
        self._hh_sampled.offer(uniq, sums)
        for n in sums.tolist():
            self._p2["obs_count_p50"].add(n)
            self._p2["obs_count_p95"].add(n)
        for gap, _w in self._kmv.observe(rnd, uniq, sums):
            self._p2["gap_p50"].add(gap)
            self._p2["gap_p95"].add(gap)

    def observe_loss_argmax(self, client_id: Optional[int]) -> None:
        """One round's highest-loss client (the client_stats
        quantiles[...]["argmax_client"] channel); weight 1 per round."""
        if client_id is not None:
            self._hh_loss.offer([int(client_id)], [1.0])

    def observe_strikes(self, client_ids: Sequence[int]) -> None:
        """Quarantine strikes this round (core/quarantine.py ledger);
        weight 1 per strike."""
        ids = np.asarray(list(client_ids), np.int64).reshape(-1)
        if ids.size:
            self._hh_strikes.offer(ids, np.ones(ids.size))

    # ------------------------------------------------------ queries
    def participation_count(self, client_ids) -> np.ndarray:
        """Count-min estimate of per-client cumulative sample counts
        (overestimate <= cm_epsilon * total w.p. >= 1 - cm_delta)."""
        return self._cm.query(client_ids)

    @property
    def distinct(self) -> int:
        return int(round(self._kmv.distinct()))

    def memory_bytes(self) -> int:
        """Resident-footprint accounting (the documented budget model;
        the dryrun gate cross-checks it against a deep getsizeof)."""
        return int(self._cm.nbytes + self._hh_sampled.nbytes
                   + self._hh_loss.nbytes + self._hh_strikes.nbytes
                   + self._kmv.nbytes + 4 * 256)

    def snapshot(self, rnd: int) -> Dict[str, Any]:
        """Exact-ledger-compatible participation fields (client_stats
        event), plus ``estimated: True``. counts_max is the space-saving
        top-1 count — an upper estimate of the true maximum (the true
        argmax is either stored, with count >= truth, or bounded by the
        structure's minimum count)."""
        if len(self._kmv) == 0:
            return {"coverage": 0.0, "distinct_clients": 0,
                    "counts_p50": None, "counts_max": None,
                    "staleness_p50": None, "staleness_max": None,
                    "estimated": True}
        counts = self._kmv.counts()
        stale = self._kmv.staleness(rnd)
        top = self._hh_sampled.top(1)
        return {
            "coverage": min(1.0, self._kmv.distinct() / self.num_clients),
            "distinct_clients": self.distinct,
            "counts_p50": float(np.percentile(counts, 50)),
            "counts_max": float(top[0][1]) if top else float(counts.max()),
            "staleness_p50": float(np.percentile(stale, 50)),
            "staleness_max": float(stale.max()),
            "estimated": True,
        }

    def population_snapshot(self, rnd: int) -> Dict[str, Any]:
        """The schema-v11 ``population`` event body (POPULATION_KEYS)."""
        base = self.snapshot(rnd)
        counts = self._kmv.counts()
        stale = self._kmv.staleness(rnd)
        have = counts.size > 0
        return {
            "round": int(rnd),
            "estimated": True,
            "registered": self.num_clients,
            "distinct": float(self._kmv.distinct()),
            "coverage": base["coverage"],
            "counts_p50": base["counts_p50"],
            "counts_p95": float(np.percentile(counts, 95)) if have else None,
            "counts_max": base["counts_max"],
            "staleness_p50": base["staleness_p50"],
            "staleness_p95": (float(np.percentile(stale, 95))
                              if have else None),
            "staleness_max": base["staleness_max"],
            "obs_count_p50": self._p2["obs_count_p50"].value(),
            "obs_count_p95": self._p2["obs_count_p95"].value(),
            "gap_p50": self._p2["gap_p50"].value(),
            "gap_p95": self._p2["gap_p95"].value(),
            "top_sampled": [e[:2] for e in self._hh_sampled.top(10)],
            "top_loss": [e[:2] for e in self._hh_loss.top(10)],
            "top_strikes": [e[:2] for e in self._hh_strikes.top(10)],
            "memory_bytes": float(self.memory_bytes()),
            "cm_epsilon": self._cm.epsilon,
            "cm_delta": self._cm.delta,
            "hh_k": self._hh_sampled.k,
            "sample_size": self._kmv.size,
        }

    # ------------------------------------------------------ persistence
    def state_dict(self) -> Dict[str, Any]:
        """Checkpoint-sidecar state (core/preempt.py). Canonical and
        bitwise-stable: identical observation streams yield identical
        JSON regardless of kill/resume boundaries."""
        return {
            "sketch": True,
            "num_clients": self.num_clients,
            "seed": self.seed,
            "cm": self._cm.state_dict(),
            "hh_sampled": self._hh_sampled.state_dict(),
            "hh_loss": self._hh_loss.state_dict(),
            "hh_strikes": self._hh_strikes.state_dict(),
            "kmv": self._kmv.state_dict(),
            "p2": {k: v.state_dict() for k, v in self._p2.items()},
        }

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        if not d:
            return
        if not d.get("sketch"):
            raise ValueError(
                "checkpoint ledger sidecar holds EXACT participation "
                "state but this run uses --population_sketch on; resume "
                "with the ledger mode the checkpoint was written under "
                "(or drop the sidecar to start coverage accounting fresh)")
        self.num_clients = int(d.get("num_clients", self.num_clients))
        self.seed = int(d.get("seed", self.seed))
        self._cm.load_state_dict(d["cm"])
        self._hh_sampled.load_state_dict(d["hh_sampled"])
        self._hh_loss.load_state_dict(d["hh_loss"])
        self._hh_strikes.load_state_dict(d["hh_strikes"])
        self._kmv.load_state_dict(d["kmv"])
        for k, v in (d.get("p2") or {}).items():
            if k in self._p2:
                self._p2[k].load_state_dict(v)
