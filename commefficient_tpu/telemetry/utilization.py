"""Utilization accounting: achieved FLOP/s, MFU, and starvation fractions.

Joins the three measurements the run already produces but never
combined: per-round wall-time phases (host batch assembly / async
dispatch / ``block_until_ready`` device wait), the compiled round's
cost-analysis FLOPs (``compilewatch.JitWatcher`` records them per
watched executable), and a per-``device_kind`` peak-FLOPs table
(overridable with ``--peak_flops``) — and emits schema-validated
``utilization`` events so "is the chip busy, and if not, who is
starving it" is a stream field instead of a profiler session.

Conventions
-----------
- **MFU** is model/executable FLOPs per wall-clock second over the
  chip's peak: ``flops_per_round * rounds / (wall_s * peak)``. The wall
  clock is the full window (including host time) — input starvation
  LOWERS MFU, by design; ``input_wait_frac`` says how much.
- ``flops_source`` records where the numerator came from:
  ``cost_analysis`` (XLA's count for the compiled round — trustworthy
  for un-scanned rounds, an under-count for scanned ones, see
  bench_gpt2.py) or ``analytic`` (caller-provided closed form). A null
  ``flops_per_round`` yields null ``mfu``, never a fake zero.
- ``input_wait_frac`` / ``dispatch_frac`` / ``device_wait_frac`` are
  fractions of the window's wall time. Device waits are only measured
  on rounds that synced (the telemetry record cadence), so the three
  fractions need not sum to 1 — the remainder is untimed loop tail.
- ``straggler_spread`` is ``(max - min) / mean`` of per-host device
  times on a multi-host mesh; null when only one host reported.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, List, Optional, Tuple

# peak bf16 FLOP/s by accelerator generation (public spec sheets),
# matched by device_kind PREFIX. The single source of truth —
# bench_common.peak_flops reads this table.
PEAK_FLOPS_BY_KIND = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v3": 123e12,
    "TPU v2": 45e12,
}

# peak HBM bandwidth in GB/s by generation (public spec sheets), same
# prefix matching and same single-source rule as the FLOPs table — the
# roofline's second axis. An unknown chip yields null bandwidth fields
# (--peak_hbm_gbps overrides), never a guess.
PEAK_HBM_GBPS_BY_KIND = {
    "TPU v5 lite": 819.0,    # v5e
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,        # v5p
    "TPU v4": 1228.0,
    "TPU v6 lite": 1640.0,   # v6e / Trillium
    "TPU v3": 900.0,
    "TPU v2": 700.0,
}

# roofline attribution fields added to the ``utilization`` event in
# schema v6 — computed by roofline_fields below; scripts/teleview.py
# mirrors these as literals for jax-free analysis, pinned by
# tests/test_memory.py.
ROOFLINE_KEYS = ("peak_hbm_gbps", "bytes_per_round", "bytes_source",
                 "arithmetic_intensity", "ridge_intensity", "bound",
                 "achieved_gbps", "bw_frac", "expected_round_s")


def _peak_lookup(table, device_kind: str,
                 override: float = 0.0) -> Optional[float]:
    if override:
        return float(override)
    for name, peak in table.items():
        if device_kind.startswith(name):
            return peak
    return None


def peak_flops_for(device_kind: str,
                   override: float = 0.0) -> Optional[float]:
    """Peak FLOP/s for a device kind: the ``--peak_flops`` override when
    given, else the table (prefix match), else None — an unknown chip
    yields null MFU rather than a number computed against a guess."""
    return _peak_lookup(PEAK_FLOPS_BY_KIND, device_kind, override)


def peak_hbm_for(device_kind: str,
                 override: float = 0.0) -> Optional[float]:
    """Peak HBM bandwidth (GB/s): the ``--peak_hbm_gbps`` override when
    given, else the table (prefix match), else None — same
    null-never-fake-zero contract as peak_flops_for."""
    return _peak_lookup(PEAK_HBM_GBPS_BY_KIND, device_kind, override)


def roofline_fields(*, rounds: int, wall_s: float,
                    flops_per_round: Optional[float],
                    bytes_per_round: Optional[float],
                    bytes_source: Optional[str],
                    peak_flops: Optional[float],
                    peak_hbm_gbps: Optional[float]) -> Dict[str, Any]:
    """Roofline attribution for one executable over one timed window:

    - ``arithmetic_intensity`` = FLOPs / bytes accessed (FLOP/byte);
    - ``ridge_intensity`` = peak FLOP/s / peak bytes/s — the intensity
      where the roofline's two ceilings meet on THIS chip;
    - ``bound``: ``compute`` when the intensity sits at/right of the
      ridge (the FLOP ceiling binds), ``bandwidth`` left of it (the HBM
      ceiling binds), null when either coordinate is unknown;
    - ``achieved_gbps`` / ``bw_frac``: measured byte throughput and its
      fraction of peak — the bandwidth analog of achieved_flops / mfu;
    - ``expected_round_s``: the two-term time model
      max(flops/peak_flops, bytes/peak_bw) — the executable's floor
      under perfect overlap; wall clock above it is overhead
      (dispatch, serialization, under-utilized units), below it means
      the byte or FLOP count under-describes the executable.

    Every field is null when an input it needs is unknown — a roofline
    verdict computed against a guessed peak would be exactly the
    back-of-envelope arithmetic this module exists to replace."""
    peak_bw = peak_hbm_gbps * 1e9 if peak_hbm_gbps else None
    ai = (flops_per_round / bytes_per_round
          if flops_per_round and bytes_per_round else None)
    ridge = (peak_flops / peak_bw if peak_flops and peak_bw else None)
    bound = None
    if ai is not None and ridge is not None:
        bound = "compute" if ai >= ridge else "bandwidth"
    achieved_bps = (bytes_per_round * rounds / wall_s
                    if bytes_per_round and wall_s > 0 else None)
    t_flops = (flops_per_round / peak_flops
               if flops_per_round and peak_flops else None)
    t_bytes = (bytes_per_round / peak_bw
               if bytes_per_round and peak_bw else None)
    expected = (max(t_flops, t_bytes)
                if t_flops is not None and t_bytes is not None else None)

    def sig(v, figs=6):
        # significant figures like mfu: tiny true values must not
        # round to a dishonest 0.0
        return float(f"{v:.{figs}g}") if v is not None else None

    return {
        "peak_hbm_gbps": peak_hbm_gbps,
        "bytes_per_round": bytes_per_round,
        "bytes_source": bytes_source if bytes_per_round else None,
        "arithmetic_intensity": sig(ai),
        "ridge_intensity": sig(ridge),
        "bound": bound,
        "achieved_gbps": sig(achieved_bps / 1e9
                             if achieved_bps is not None else None),
        "bw_frac": sig(achieved_bps / peak_bw
                       if achieved_bps is not None and peak_bw else None),
        "expected_round_s": sig(expected),
    }


def _frac(part: float, whole: float) -> Optional[float]:
    return round(part / whole, 6) if whole > 0 else None


def straggler_spread(per_host_device_s: List[float]) -> Optional[float]:
    """(max - min) / mean of per-host device times — 0 on a perfectly
    balanced mesh, grows with the slowest host's lag. None below two
    hosts (a single host cannot straggle against itself)."""
    ts = [float(t) for t in per_host_device_s if t is not None]
    if len(ts) < 2:
        return None
    mean = sum(ts) / len(ts)
    if mean <= 0:
        return None
    return round((max(ts) - min(ts)) / mean, 6)


def utilization_fields(*, rounds: int, wall_s: float,
                       host_s: float, dispatch_s: float, device_s: float,
                       flops_per_round: Optional[float],
                       flops_source: Optional[str],
                       device_kind: str,
                       peak_flops: Optional[float],
                       spread: Optional[float] = None,
                       bytes_per_round: Optional[float] = None,
                       bytes_source: Optional[str] = None,
                       peak_hbm_gbps: Optional[float] = None,
                       n_devices: Optional[int] = None,
                       mesh_shape: Optional[List[int]] = None
                       ) -> Dict[str, Any]:
    """The pure MFU/starvation math, separated from event emission so
    tests can drive it with synthetic cost dicts and fake peak tables.
    Schema v6: joins the roofline attribution (roofline_fields) when a
    byte count / bandwidth peak is supplied — null fields otherwise.
    Schema v7: carries the window's mesh topology (``n_devices`` /
    ``mesh_shape``) so per-chip throughput — the weak-scaling contract
    scripts/scaling_curves.py gates — is computable from the stream
    alone; null when the caller knows neither, never a fake 1."""
    achieved = mfu = None
    if flops_per_round and wall_s > 0:
        achieved = flops_per_round * rounds / wall_s
        if peak_flops:
            mfu = achieved / peak_flops
    return {
        "n_devices": int(n_devices) if n_devices else None,
        "mesh_shape": (list(int(x) for x in mesh_shape)
                       if mesh_shape is not None else None),
        "rounds": int(rounds),
        "wall_s": round(wall_s, 6),
        "device_kind": device_kind,
        "peak_flops": peak_flops,
        "flops_per_round": flops_per_round,
        "flops_source": flops_source if flops_per_round else None,
        "achieved_flops": achieved,
        # significant figures, not decimal places: a smoke-model mfu of
        # 2e-8 must not round to a (dishonest) 0.0
        "mfu": (float(f"{mfu:.6g}") if mfu is not None else None),
        "input_wait_frac": _frac(host_s, wall_s),
        "dispatch_frac": _frac(dispatch_s, wall_s),
        "device_wait_frac": _frac(device_s, wall_s),
        "straggler_spread": spread,
        **roofline_fields(rounds=rounds, wall_s=wall_s,
                          flops_per_round=flops_per_round,
                          bytes_per_round=bytes_per_round,
                          bytes_source=bytes_source,
                          peak_flops=peak_flops,
                          peak_hbm_gbps=peak_hbm_gbps),
    }


def emit_from_totals(telemetry, *, rnd: int, rounds: int, wall_s: float,
                     host_s: float = 0.0, dispatch_s: float = 0.0,
                     device_s: float = 0.0,
                     flops_per_round: Optional[float] = None,
                     flops_source: Optional[str] = None,
                     device_kind: str = "unknown",
                     peak_flops: float = 0.0,
                     per_host_device_s: Optional[List[float]] = None,
                     bytes_per_round: Optional[float] = None,
                     bytes_source: Optional[str] = None,
                     peak_hbm_gbps: float = 0.0,
                     n_devices: Optional[int] = None,
                     mesh_shape: Optional[List[int]] = None
                     ) -> Dict[str, Any]:
    """One-shot ``utilization`` event from aggregate totals (the bench
    path: one event per timed stage). Returns the computed fields so the
    caller can fold them into its JSON artifact too."""
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    fields = utilization_fields(
        rounds=rounds, wall_s=wall_s, host_s=host_s, dispatch_s=dispatch_s,
        device_s=device_s, flops_per_round=flops_per_round,
        flops_source=flops_source, device_kind=device_kind,
        peak_flops=peak_flops_for(device_kind, peak_flops),
        spread=straggler_spread(per_host_device_s or []),
        bytes_per_round=bytes_per_round, bytes_source=bytes_source,
        peak_hbm_gbps=peak_hbm_for(device_kind, peak_hbm_gbps),
        n_devices=n_devices, mesh_shape=mesh_shape)
    if telemetry is not None:
        telemetry.event("utilization", round=int(rnd), **fields)
    return fields


class UtilizationTracker:
    """Windowed utilization accounting for a driver's round loop.

    ``observe_round`` is called every round with the measured phase
    times (``device_s=None`` on rounds that did not sync); ``emit`` —
    called at the telemetry record cadence, outside the timed region —
    joins the window's phase sums with the watched round executable's
    cost-analysis FLOPs and writes one ``utilization`` event, then
    resets the window. The window wall clock runs from the first
    observed round (monotonic ``perf_counter``), so untimed loop tail
    (telemetry emission itself) is included in the denominator — MFU is
    honest about everything the loop spends.
    """

    def __init__(self, telemetry, *, device_kind: Optional[str] = None,
                 peak_flops: float = 0.0, watcher=None,
                 watch_name: str = "round_step",
                 peak_hbm_gbps: float = 0.0,
                 n_devices: Optional[int] = None,
                 mesh_shape: Optional[List[int]] = None):
        self._telemetry = telemetry
        self._watcher = watcher
        self._watch_name = watch_name
        if device_kind is None or n_devices is None:
            import jax
            devices = jax.devices()
            if device_kind is None:
                device_kind = (getattr(devices[0], "device_kind",
                                       "unknown")
                               if devices else "none")
            if n_devices is None:
                n_devices = len(devices)
        self.n_devices = n_devices
        self.mesh_shape = mesh_shape
        self.device_kind = device_kind
        self.peak_flops = peak_flops_for(device_kind, peak_flops)
        if self.peak_flops is None:
            print(f"WARNING: no peak-FLOPs entry for device kind "
                  f"{device_kind!r}; utilization events will carry null "
                  "mfu (set --peak_flops to override)", file=sys.stderr)
        self.peak_hbm_gbps = peak_hbm_for(device_kind, peak_hbm_gbps)
        if self.peak_hbm_gbps is None:
            print(f"WARNING: no peak-HBM-bandwidth entry for device kind "
                  f"{device_kind!r}; utilization events will carry null "
                  "roofline fields (set --peak_hbm_gbps to override)",
                  file=sys.stderr)
        self._flops: Optional[float] = None
        self._flops_source: Optional[str] = None
        self._reset()

    def _reset(self) -> None:
        self._win_t0: Optional[float] = None
        self._rounds = 0
        self._host_s = self._dispatch_s = self._device_s = 0.0
        self._per_host: List[float] = []

    def set_flops_per_round(self, flops: Optional[float],
                            source: str = "analytic") -> None:
        """Pin the MFU numerator (e.g. an analytic count where XLA's
        cost analysis under-reports scanned rounds)."""
        self._flops = flops
        self._flops_source = source if flops else None

    def _flops_per_round(self) -> Tuple[Optional[float], Optional[str]]:
        if self._flops is not None:
            return self._flops, self._flops_source
        if self._watcher is not None:
            flops = getattr(self._watcher, "flops", {}).get(self._watch_name)
            if flops:
                return float(flops), "cost_analysis"
        return None, None

    def _bytes_per_round(self) -> Tuple[Optional[float], Optional[str]]:
        """Roofline byte numerator: the watched executable's
        cost-analysis bytes-accessed (compilewatch.JitWatcher records it
        per compile). No analytic override — there is no closed-form
        bytes count the way there is for FLOPs; null when unknown."""
        if self._watcher is not None:
            b = getattr(self._watcher, "bytes", {}).get(self._watch_name)
            if b:
                return float(b), "cost_analysis"
        return None, None

    def observe_round(self, *, host_s: float, dispatch_s: float,
                      device_s: Optional[float] = None) -> None:
        if self._win_t0 is None:
            # anchor at the observed round's start, not at emit time
            self._win_t0 = time.perf_counter() - (
                host_s + dispatch_s + (device_s or 0.0))
        self._rounds += 1
        self._host_s += host_s
        self._dispatch_s += dispatch_s
        if device_s is not None:
            self._device_s += device_s

    def observe_host_device_times(self, per_host_device_s: List[float]
                                  ) -> None:
        """Per-host device times for one round on a multi-host mesh
        (multihost runners feed this; single-host runs never call it)."""
        self._per_host = list(per_host_device_s)

    def emit(self, rnd: int) -> Optional[Dict[str, Any]]:
        """Emit one ``utilization`` event over the window since the last
        emit; no-op (returns None) on an empty window."""
        if self._rounds == 0 or self._telemetry is None:
            return None
        wall = time.perf_counter() - self._win_t0
        flops, source = self._flops_per_round()
        nbytes, bsource = self._bytes_per_round()
        fields = utilization_fields(
            rounds=self._rounds, wall_s=wall, host_s=self._host_s,
            dispatch_s=self._dispatch_s, device_s=self._device_s,
            flops_per_round=flops, flops_source=source,
            device_kind=self.device_kind, peak_flops=self.peak_flops,
            spread=straggler_spread(self._per_host),
            bytes_per_round=nbytes, bytes_source=bsource,
            peak_hbm_gbps=self.peak_hbm_gbps,
            n_devices=self.n_devices, mesh_shape=self.mesh_shape)
        self._telemetry.event("utilization", round=int(rnd), **fields)
        self._reset()
        return fields
