"""Profiler window management: ``--profile_rounds START:STOP``.

Replaces the window hardcoded to rounds 2-4 of ``cv_train.py`` only:
every driver (cv_train, gpt2_train) and both benchmarks now place the
jax profiler trace over an arbitrary round range of the run. Rounds are
1-based and the window is inclusive — the default "2:4" captures rounds
2, 3 and 4, exactly the old behavior (skipping round 1 keeps the first
compile out of the trace).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple


def parse_profile_rounds(spec: str) -> Tuple[int, int]:
    """Parse "START:STOP" (inclusive, 1-based). A bare "N" profiles the
    single round N. Raises ValueError with an actionable message."""
    s = spec.strip()
    try:
        if ":" in s:
            a, b = s.split(":", 1)
            start, stop = int(a), int(b)
        else:
            start = stop = int(s)
    except ValueError:
        raise ValueError(
            f"--profile_rounds {spec!r} is not START:STOP (two integers, "
            "e.g. '2:4') or a single round number") from None
    if start < 1 or stop < start:
        raise ValueError(
            f"--profile_rounds {spec!r}: need 1 <= START <= STOP")
    return start, stop


class ProfilerWindow:
    """Start/stop a jax profiler trace over a round window.

    ``maybe_start(rnd)`` goes before the round's dispatch and
    ``maybe_stop(rnd, sync)`` after it; ``sync`` is called before
    stopping so the trace contains completed device work (a
    ``block_until_ready`` on something the round produced). ``abort()``
    closes a live trace on an error path — a retried benchmark attempt
    must not leak an open trace into the profiler's global state.
    """

    def __init__(self, outdir: str, rounds: str = "2:4",
                 log: Callable[[str], None] = print):
        self.outdir = outdir
        self.start, self.stop = (parse_profile_rounds(rounds) if outdir
                                 else (0, 0))
        self._log = log
        self.active = False
        self.done = False

    @property
    def enabled(self) -> bool:
        return bool(self.outdir)

    def maybe_start(self, rnd: int) -> None:
        if (self.enabled and not self.done and not self.active
                and self.start <= rnd <= self.stop):
            import jax
            jax.profiler.start_trace(self.outdir)
            self.active = True

    def maybe_stop(self, rnd: int,
                   sync: Optional[Callable[[], None]] = None) -> None:
        if self.active and rnd >= self.stop:
            import jax
            if sync is not None:
                sync()
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            self._log(f"profiler trace written to {self.outdir}")

    def finalize(self, sync: Optional[Callable[[], None]] = None) -> None:
        """Close a window the run ended inside of (STOP beyond the last
        round, a NaN abort, a fractional final epoch): the rounds captured
        so far still become a trace — and the profiler's process-global
        state is released — instead of silently losing both. No-op when
        the window already closed (or never opened)."""
        if self.active:
            import jax
            if sync is not None:
                sync()
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            self._log(f"profiler trace written to {self.outdir} "
                      "(window closed early: run ended before round "
                      f"{self.stop})")

    def abort(self) -> None:
        if self.active:
            self.active = False
            self.done = True
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
