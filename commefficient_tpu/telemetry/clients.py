"""Per-client population statistics: what each of the round's W clients
actually did, without ever shipping a per-client vector off device.

FetchSGD federates a client POPULATION, but until this module only the
population's mean loss and summed bytes left the jitted round — a single
diverging client, a DP clip that saturates for half the cohort, or a
participation skew that starves most of the universe were all invisible
until they surfaced as an aggregate NaN. Two halves close that gap:

- **Device side** (:func:`summarize_per_client`, called inside
  ``FedRuntime._round_step``): per-client scalars — loss, gradient norm
  pre/post clip, clip saturation, update-contribution norm, exact bytes
  — are reduced along the existing client vmap axis to quantile
  summaries (p5/p25/p50/p75/p95/max/mean + argmax slot). Only those
  scalars ride the round's async metrics fetch, so the JSONL cost is
  independent of ``num_workers`` and there is no extra host sync.
  Everything is gated exactly like signals.py: computed only when a
  telemetry stream exists to read it (``FedRuntime._client_stats``), and
  compiled out entirely under ``--no_telemetry`` / ``--no_client_stats``
  (identity-tested in tests/test_clients.py).

- **Host side** (:class:`ParticipationLedger`): per-client sample
  counts, coverage fraction and staleness, accumulated from the
  sampler's (host-resident) ``client_ids``/``mask`` every round — no
  device traffic — and snapshotted into the same schema-v3
  ``client_stats`` event at the record cadence.

NaN means "not applicable for this mode/path" (e.g. per-client gradient
norms under the fused-clients fast path, where no per-client gradient
ever materializes) and serializes as JSON null — never silently zero,
the signals.py convention.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

# per-client scalars the CLIENT step can produce (core/client.py); the
# round adds "loss" (results[0] is already per-client) and, under
# --track_bytes, the exact per-slot byte costs
CLIENT_GRAD_KEYS = ("grad_norm_pre", "grad_norm_post", "clip_frac",
                    "tx_norm")
CLIENT_STAT_KEYS = ("loss",) + CLIENT_GRAD_KEYS + ("upload_bytes",
                                                   "download_bytes")
QUANTILE_PCTS = (5.0, 25.0, 50.0, 75.0, 95.0)
QUANTILE_FIELDS = ("p5", "p25", "p50", "p75", "p95", "max", "mean")


def summarize_per_client(per_client: Dict[str, Any], n_valid: Any,
                         replicate_fn=None) -> Dict[str, Dict[str, Any]]:
    """On-device quantile reduction of per-client (W,) stat vectors.

    Traced inside the jitted round step. Slots whose client processed no
    valid datum (fully-padded rounds) are excluded via NaN-masking;
    stats that arrive as NaN (not applicable) stay NaN through the
    quantiles. Returns ``{key: {"q": (5,) array, "max": (), "mean": (),
    "argmax": () int}}`` — the host maps ``argmax`` (a round SLOT) to a
    real client id via the round's ``client_ids``.

    Every stat is stacked into ONE (K, W) matrix before the reduction,
    and on a mesh the runtime passes ``replicate_fn`` (a sharding
    constraint to replicated): one W-sized all-gather covers the whole
    summary, instead of per-key quantile reductions each lowering to
    their own cross-device collectives (measured: ~30 extra tiny
    all-reduces per round without this — the very launch-count
    pathology the collective ledger exists to catch).
    """
    import jax.numpy as jnp

    keys = sorted(per_client)
    mat = jnp.stack([jnp.asarray(per_client[k], jnp.float32)
                     for k in keys])                       # (K, W)
    valid = jnp.asarray(n_valid) > 0
    if replicate_fn is not None:
        mat = replicate_fn(mat)
        valid = replicate_fn(valid)
    masked = jnp.where(valid[None, :], mat, jnp.nan)
    finite = valid[None, :] & jnp.isfinite(mat)
    pcts = jnp.asarray(QUANTILE_PCTS, jnp.float32)
    q = jnp.nanpercentile(masked, pcts, axis=1)            # (5, K)
    mx = jnp.nanmax(masked, axis=1)
    mean = jnp.nanmean(masked, axis=1)
    # argmax over valid finite entries only; meaningless (and nulled by
    # the host conversion) when max itself is NaN
    arg = jnp.argmax(jnp.where(finite, mat, -jnp.inf), axis=1)
    return {k: {"q": q[:, i].astype(jnp.float32),
                "max": mx[i].astype(jnp.float32),
                "mean": mean[i].astype(jnp.float32),
                "argmax": arg[i]}
            for i, k in enumerate(keys)}


def client_stats_to_host(summary: Optional[Dict[str, Dict[str, Any]]],
                         client_ids) -> Dict[str, Dict[str, Any]]:
    """Fetch a device summary (the caller has synced the metrics pytree)
    into the ``quantiles`` dict of a ``client_stats`` event: every key
    maps to {p5,...,p95,max,mean,argmax_client}, non-finite -> None."""
    if not summary:
        return {}
    try:
        # ONE batched device->host fetch of the whole pytree: the
        # per-field float() conversions below would otherwise each
        # issue their own synchronous transfer (~50 per event)
        import jax
        summary = jax.device_get(summary)
    except ImportError:  # plain-numpy summaries (tests, offline tools)
        pass
    ids = np.asarray(client_ids)

    def fin(x) -> Optional[float]:
        x = float(np.asarray(x))
        return x if np.isfinite(x) else None

    out: Dict[str, Dict[str, Any]] = {}
    for key, s in summary.items():
        q = np.asarray(s["q"], np.float64)
        rec: Dict[str, Any] = {
            name: fin(q[i]) for i, name in enumerate(
                ("p5", "p25", "p50", "p75", "p95"))}
        rec["max"] = fin(s["max"])
        rec["mean"] = fin(s["mean"])
        slot = int(np.asarray(s["argmax"]))
        rec["argmax_client"] = (int(ids[slot])
                                if rec["max"] is not None
                                and 0 <= slot < len(ids) else None)
        out[key] = rec
    return out


def quantiles_ordered(rec: Dict[str, Any]) -> bool:
    """p5 <= p25 <= ... <= p95 <= max over the non-null fields of one
    stat's quantile record — the dryrun/test sanity predicate."""
    seq = [rec.get(k) for k in ("p5", "p25", "p50", "p75", "p95", "max")]
    seq = [v for v in seq if v is not None]
    return all(a <= b + 1e-9 for a, b in zip(seq, seq[1:]))


class ParticipationLedger:
    """Host-side participation accounting for the client universe.

    ``observe`` is called every round with the sampler's host-resident
    ``client_ids`` and per-slot valid-datum counts (no device fetch);
    ``snapshot`` folds the ledger into the participation fields of a
    ``client_stats`` event: coverage (distinct participants over the
    universe), per-seen-client sample-count quantiles, and staleness
    (rounds since each seen client last participated).
    """

    estimated = False

    def __init__(self, num_clients: int):
        self.num_clients = max(int(num_clients), 1)
        self._samples: Dict[int, float] = {}
        self._last_round: Dict[int, int] = {}
        self._loss_wins: Dict[int, float] = {}
        self._strikes: Dict[int, float] = {}
        from commefficient_tpu.telemetry.population import P2Quantile
        self._p2 = {"obs_count_p50": P2Quantile(0.50),
                    "obs_count_p95": P2Quantile(0.95),
                    "gap_p50": P2Quantile(0.50),
                    "gap_p95": P2Quantile(0.95)}

    def observe(self, rnd: int, client_ids, samples_per_slot=None) -> None:
        # zero-sample slots did not participate: the async scenario
        # engine's partial-participation masking zeroes whole slots
        # (data/scenarios.py), and crediting them would reset the
        # client's staleness without it having contributed anything.
        # Sync rounds never produce these (the sampler only yields
        # slots with data). _aggregate drops them, dedups repeated ids
        # within the batch and returns ascending unique ids — the bulk
        # form of the old per-slot loop (equivalence pinned in
        # tests/test_population.py).
        from commefficient_tpu.telemetry.population import _aggregate
        uniq, sums = _aggregate(client_ids, samples_per_slot)
        rnd = int(rnd)
        for c, n in zip(uniq.tolist(), sums.tolist()):
            c = int(c)
            prev = self._last_round.get(c)
            if prev is not None:
                self._p2["gap_p50"].add(rnd - prev)
                self._p2["gap_p95"].add(rnd - prev)
            self._samples[c] = self._samples.get(c, 0.0) + float(n)
            self._last_round[c] = rnd
            self._p2["obs_count_p50"].add(n)
            self._p2["obs_count_p95"].add(n)

    def observe_loss_argmax(self, client_id: Optional[int]) -> None:
        """One round's highest-loss client (the client_stats
        quantiles[...]["argmax_client"] channel); weight 1 per round."""
        if client_id is not None:
            c = int(client_id)
            self._loss_wins[c] = self._loss_wins.get(c, 0.0) + 1.0

    def observe_strikes(self, client_ids: Sequence[int]) -> None:
        """Quarantine strikes this round (core/quarantine.py ledger)."""
        for c in client_ids:
            c = int(c)
            self._strikes[c] = self._strikes.get(c, 0.0) + 1.0

    @property
    def distinct(self) -> int:
        return len(self._samples)

    def state_dict(self) -> Dict[str, Any]:
        """JSON-serializable ledger state for checkpoint meta: a resumed
        run keeps its coverage/staleness view of the universe instead of
        reporting coverage ~0 until every client is re-seen."""
        return {
            "samples": {str(c): n for c, n in self._samples.items()},
            "last_round": {str(c): r
                           for c, r in self._last_round.items()},
            "loss_wins": {str(c): n for c, n in self._loss_wins.items()},
            "strikes": {str(c): n for c, n in self._strikes.items()},
            "p2": {k: v.state_dict() for k, v in self._p2.items()},
        }

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        if d and d.get("sketch"):
            raise ValueError(
                "checkpoint ledger sidecar holds SKETCH participation "
                "state (--population_sketch on) but this run uses the "
                "exact ledger; resume with the ledger mode the "
                "checkpoint was written under (or drop the sidecar to "
                "start coverage accounting fresh)")
        self._samples = {int(c): float(n)
                         for c, n in (d.get("samples") or {}).items()}
        self._last_round = {int(c): int(r)
                            for c, r in (d.get("last_round") or {}).items()}
        # pre-v11 sidecars legitimately lack the heavy-hitter / P2 keys
        self._loss_wins = {int(c): float(n)
                           for c, n in (d.get("loss_wins") or {}).items()}
        self._strikes = {int(c): float(n)
                         for c, n in (d.get("strikes") or {}).items()}
        for k, v in (d.get("p2") or {}).items():
            if k in self._p2:
                self._p2[k].load_state_dict(v)

    def snapshot(self, rnd: int) -> Dict[str, Any]:
        if not self._samples:
            return {"coverage": 0.0, "distinct_clients": 0,
                    "counts_p50": None, "counts_max": None,
                    "staleness_p50": None, "staleness_max": None,
                    "estimated": False}
        counts = np.fromiter(self._samples.values(), np.float64)
        stale = np.asarray([rnd - lr for lr in self._last_round.values()],
                           np.float64)
        return {
            "coverage": len(counts) / self.num_clients,
            "distinct_clients": int(len(counts)),
            "counts_p50": float(np.percentile(counts, 50)),
            "counts_max": float(counts.max()),
            "staleness_p50": float(np.percentile(stale, 50)),
            "staleness_max": float(stale.max()),
            "estimated": False,
        }

    def memory_bytes(self) -> int:
        """Resident-footprint model: ~76B per dict entry (int key +
        float value + slot), 4 dicts — O(population), which is exactly
        why :mod:`~commefficient_tpu.telemetry.population` exists."""
        n = (len(self._samples) + len(self._last_round)
             + len(self._loss_wins) + len(self._strikes))
        return n * 76 + 4 * 256

    def population_snapshot(self, rnd: int) -> Dict[str, Any]:
        """The schema-v11 ``population`` event body — same fields as
        PopulationLedger.population_snapshot, exact values, sketch
        parameters null, ``estimated: False``. The obs_count/gap
        quantiles are P2 estimates in BOTH modes (the per-participation
        streams are unbounded); everything else here is exact."""
        def top10(d: Dict[int, float]):
            order = sorted(d, key=lambda c: (-d[c], c))[:10]
            return [[int(c), float(d[c])] for c in order]

        base = self.snapshot(rnd)
        have = bool(self._samples)
        counts = (np.fromiter(self._samples.values(), np.float64)
                  if have else None)
        stale = (np.asarray([rnd - lr
                             for lr in self._last_round.values()],
                            np.float64) if have else None)
        return {
            "round": int(rnd),
            "estimated": False,
            "registered": self.num_clients,
            "distinct": float(len(self._samples)),
            "coverage": base["coverage"],
            "counts_p50": base["counts_p50"],
            "counts_p95": (float(np.percentile(counts, 95))
                           if have else None),
            "counts_max": base["counts_max"],
            "staleness_p50": base["staleness_p50"],
            "staleness_p95": (float(np.percentile(stale, 95))
                              if have else None),
            "staleness_max": base["staleness_max"],
            "obs_count_p50": self._p2["obs_count_p50"].value(),
            "obs_count_p95": self._p2["obs_count_p95"].value(),
            "gap_p50": self._p2["gap_p50"].value(),
            "gap_p95": self._p2["gap_p95"].value(),
            "top_sampled": top10(self._samples),
            "top_loss": top10(self._loss_wins),
            "top_strikes": top10(self._strikes),
            "memory_bytes": float(self.memory_bytes()),
            "cm_epsilon": None,
            "cm_delta": None,
            "hh_k": None,
            "sample_size": None,
        }


def make_ledger(num_clients: int, population_sketch: str = "auto", *,
                seed: int = 0):
    """Ledger construction policy for the drivers: ``auto`` uses the
    exact ledger below :data:`~commefficient_tpu.telemetry.population.
    AUTO_SKETCH_THRESHOLD` registered clients and the bounded-memory
    sketch ledger at/above it; ``on``/``off`` force the choice. Both
    ledgers emit identical event fields; only ``estimated`` differs."""
    from commefficient_tpu.telemetry.population import (
        AUTO_SKETCH_THRESHOLD, PopulationLedger)
    if population_sketch not in ("auto", "on", "off"):
        raise ValueError(f"population_sketch must be auto|on|off, "
                         f"got {population_sketch!r}")
    sketch = (population_sketch == "on"
              or (population_sketch == "auto"
                  and int(num_clients) >= AUTO_SKETCH_THRESHOLD))
    if sketch:
        return PopulationLedger(num_clients, seed=seed)
    return ParticipationLedger(num_clients)
