"""RunTelemetry: the machine-readable event stream of one run.

Writes ``telemetry.jsonl`` (schema.py) into the run's logdir next to
whatever else the run records (tensorboard events, traces). The console
TableLogger/TSVLogger output is deliberately untouched: telemetry is a
parallel channel, not a replacement — the BENCH_r02 post-mortem (a
dropped remote-compile body nearly losing a whole benchmark artifact)
is why every event is flushed to disk the moment it happens, and why a
telemetry failure only disables telemetry, never the run.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import sys
import time
from typing import Any, Dict, Optional

from commefficient_tpu.faults import fault_matches, trigger
from commefficient_tpu.telemetry.compilewatch import JitWatcher
from commefficient_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                TELEMETRY_BASENAME)


def _jsonable(v: Any) -> Any:
    if isinstance(v, float):
        # non-finite floats serialize as null: json.dumps would emit the
        # literal NaN/Infinity tokens Python accepts but strict JSON
        # parsers (jq, JSON.parse, serde) reject — and a diverging run is
        # exactly when the stream must stay machine-readable. The schema
        # treats the metric fields as nullable for this reason.
        return v if math.isfinite(v) else None
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "item"):          # numpy / jax scalars
        try:
            return _jsonable(v.item())
        except Exception:
            pass
    return str(v)


def _sketch_geometry(cfg) -> Optional[Dict[str, Any]]:
    if getattr(cfg, "mode", None) != "sketch":
        return None
    return {
        "impl": cfg.sketch_impl,
        "num_rows": cfg.num_rows,
        "num_cols": cfg.num_cols,
        "k": cfg.k,
        "num_blocks": cfg.num_blocks,
        "ef": cfg.sketch_ef,
        "server_state": cfg.sketch_server_state,
        "dtype": cfg.sketch_dtype,
        "wire_dtype": getattr(cfg, "wire_dtype", None) or cfg.sketch_dtype,
    }


class RunTelemetry:
    """Owns the JSONL stream; one instance per run (or per benchmark
    artifact — bench.py threads its instance through bench_gpt2 so both
    stages land in the same file)."""

    def __init__(self, logdir: str, run_type: str, cfg=None,
                 manifest_extra: Optional[Dict[str, Any]] = None,
                 resume_info: Optional[Dict[str, Any]] = None):
        self.logdir = logdir
        self.run_type = run_type
        # kept for the schema-v9 wire fields: collectives/signals/bench
        # events name the run's table wire dtype (None for cfg-less
        # streams — the emitters take an explicit override)
        self.cfg = cfg
        self.path = os.path.join(logdir, TELEMETRY_BASENAME)
        self._seq = 0
        # serialize writers: the round loop owns most events, but the
        # hang watchdog's stall callback and the prefetch worker's
        # fetch-retry notes write from THEIR threads — without a lock
        # two writers could allocate the same seq (a validator-visible
        # corruption) or interleave half-lines in the shared buffer
        import threading
        # RLock: the monitor forwarding at the end of event() can fire
        # an alert that re-enters event() on the same thread
        self._lock = threading.RLock()
        # unique segment id: a resumed run appends a new manifest with a
        # fresh id, and its `resume` event names the predecessor's —
        # the crash-recovery lineage chain (schema v8)
        self.stream_id = (f"{run_type}-{os.getpid()}-"
                          f"{int(time.time() * 1000):x}")
        # durations come off the monotonic clock: an NTP step during the
        # run must not produce a negative/skewed wall_time_s. time.time()
        # stays only for the absolute `t` envelope field.
        self._t0 = time.perf_counter()
        self._file = None
        self._counts: Dict[str, int] = {}
        self._watcher: Optional[JitWatcher] = None
        self._monitor = None
        self.last_round: Optional[Dict[str, Any]] = None
        self.last_epoch: Optional[Dict[str, Any]] = None
        # ring buffer of recent serialized events — the flight recorder's
        # "last N events before it died" (telemetry/health.py); 256 covers
        # several record windows of every event type at trivial memory
        self.recent: collections.deque = collections.deque(maxlen=256)
        # recent memory (residency) snapshots, separately ring-buffered:
        # the flight recorder's memory.json wants a residency TIMELINE
        # even when the main ring has long since rotated the early
        # snapshots out under round/span traffic
        self.recent_memory: collections.deque = collections.deque(maxlen=32)
        # residency tracker (telemetry/memory_ledger.py): previous-peak
        # state for delta attribution + the one-time CPU-degradation note
        self._residency = None
        prior = None
        try:
            os.makedirs(logdir, exist_ok=True)
            if (os.path.exists(self.path)
                    and os.path.getsize(self.path) > 0):
                # NEVER clobber an existing stream with mode "w": the
                # file is a predecessor segment (a crashed or preempted
                # run pointed at the same logdir) and this run APPENDS
                # to it behind a `resume` lineage record. The prior
                # run's records — the whole point of a postmortem —
                # survive the restart.
                prior = self._scan_prior()
                self._file = open(self.path, "a")
                if prior["needs_newline"]:
                    # the predecessor died mid-line; terminate the
                    # truncated fragment so appended events stay
                    # line-delimited (the analyzer already tolerates
                    # one malformed line, schema lint flags it)
                    self._file.write("\n")
                self._seq = prior["last_seq"] + 1
            else:
                self._file = open(self.path, "w")
        except OSError as e:
            print(f"WARNING: telemetry disabled ({e})", file=sys.stderr)
            return
        info = dict(resume_info or {})
        if prior is not None:
            # segment boundary marker FIRST (lineage: which segment this
            # continues, and how far it had written), then the fresh
            # manifest — the stream's first line is still the original
            # manifest, so the shape contract holds
            self.resume_event(rnd=int(info.get("round", -1)),
                              epoch=info.get("epoch"),
                              checkpoint=info.get("checkpoint"),
                              prior_stream=prior["stream_id"],
                              prior_events=prior["last_seq"] + 1)
        self.event("manifest", schema=SCHEMA_VERSION, run_type=run_type,
                   stream_id=self.stream_id,
                   **self._environment(), **self._config_fields(cfg),
                   **(manifest_extra or {}))
        if prior is None and resume_info is not None:
            # a resumed run writing into a FRESH logdir still records
            # its lineage (checkpoint + resume round; no prior segment
            # in this file to name)
            self.resume_event(rnd=int(info.get("round", -1)),
                              epoch=info.get("epoch"),
                              checkpoint=info.get("checkpoint"),
                              prior_stream=info.get("prior_stream"),
                              prior_events=None)

    def _scan_prior(self) -> Dict[str, Any]:
        """Lineage of the existing stream this run appends to: the
        predecessor manifest's stream_id, the last valid seq (ours
        continue from there — the validator's contiguity check spans
        segments), and whether the final line was truncated mid-write.
        Streams line-by-line: a long predecessor run's file can be
        hundreds of MB, and buffering it (plus its decoded copy) would
        double the resume's peak memory for three scalar answers."""
        stream_id = None
        last_seq = -1
        with open(self.path, "r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(obj, dict):
                    continue
                if obj.get("event") == "manifest" and obj.get("stream_id"):
                    stream_id = obj["stream_id"]
                if isinstance(obj.get("seq"), int):
                    last_seq = max(last_seq, obj["seq"])
        with open(self.path, "rb") as f:
            f.seek(-1, os.SEEK_END)
            needs_newline = f.read(1) != b"\n"
        return {"stream_id": stream_id, "last_seq": last_seq,
                "needs_newline": needs_newline}

    # -------------------------------------------------------------- plumbing

    @property
    def active(self) -> bool:
        """False once the stream failed to open or was closed/disabled."""
        return self._file is not None

    @staticmethod
    def _environment() -> Dict[str, Any]:
        import jax
        devices = jax.devices()
        return {
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "device_kind": (getattr(devices[0], "device_kind", "unknown")
                            if devices else "none"),
            "device_count": len(devices),
        }

    @staticmethod
    def _config_fields(cfg) -> Dict[str, Any]:
        if cfg is None:
            return {"mesh_shape": [], "mesh_axes": [], "grad_size": 0,
                    "sketch": None, "config": {}}
        return {
            "mesh_shape": list(cfg.mesh_shape),
            "mesh_axes": list(cfg.mesh_axes),
            "grad_size": int(cfg.grad_size),
            "sketch": _sketch_geometry(cfg),
            "config": _jsonable(dataclasses.asdict(cfg)),
        }

    def event(self, kind: str, /, **fields) -> None:
        """Append one event; never raises — a full disk or closed stream
        prints one warning and disables further telemetry. The event
        type is positional-only so a field may itself be named "kind"
        (the v8 `fault` event's fault-kind). Thread-safe: writers off
        the round loop (the watchdog's stall callback, the prefetch
        worker's fetch-retry notes) serialize on the instance lock."""
        with self._lock:
            self._event_locked(kind, fields)

    def _event_locked(self, kind: str, fields) -> None:
        if self._file is None:
            return
        record = {"event": kind, "t": time.time(), "seq": self._seq}
        record.update({k: _jsonable(v) for k, v in fields.items()})
        try:
            # allow_nan=False backstops _jsonable's non-finite mapping:
            # the stream must never contain tokens strict parsers reject
            line = json.dumps(record, allow_nan=False)
            if fault_matches("mid_telemetry_flush", self._seq):
                # crash-matrix kill-point: half a line reaches the file,
                # the process dies unflushed — the resumed run's append
                # path must repair the truncated fragment
                self._file.write(line[: max(len(line) // 2, 1)])
                self._file.flush()
                os.fsync(self._file.fileno())
                trigger("mid_telemetry_flush")
                # sigterm action: trigger() RETURNS (the graceful drain
                # owns what happens next) — terminate the staged
                # fragment so the full line below starts on its own
                # line instead of merging into a permanently malformed
                # record no successor would ever repair
                self._file.write("\n")
            self._file.write(line + "\n")
            self._file.flush()
            if kind in ("alert", "nan_abort", "summary", "fault",
                        "resume"):
                # the events a postmortem reader needs most are exactly
                # the ones written while the run is dying: push them
                # through the OS cache so a crash cannot truncate them
                os.fsync(self._file.fileno())
        except (OSError, ValueError) as e:
            print(f"WARNING: telemetry write failed, disabling ({e})",
                  file=sys.stderr)
            try:
                self._file.close()
            except Exception:
                pass
            self._file = None
            return
        self._seq += 1
        self._counts[kind] = self._counts.get(kind, 0) + 1
        self.recent.append(record)
        if kind == "memory":
            self.recent_memory.append(record)
        if kind == "round":
            # last_round feeds nan_abort as "last record known FINITE":
            # a record whose loss/acc went non-finite (serialized null)
            # must not overwrite the last healthy snapshot
            if (record.get("loss") is not None
                    and record.get("acc") is not None):
                self.last_round = record
        elif kind == "epoch":
            self.last_epoch = record
        if self._monitor is not None:
            # feed the anomaly monitor AFTER serialization so it sees
            # exactly what a postmortem reader will see (NaN -> null);
            # alerts it fires come back through event() with kind
            # "alert", which is not monitored — no recursion
            from commefficient_tpu.telemetry.health import MONITORED_KINDS
            if kind in MONITORED_KINDS:
                self._monitor.observe(kind, record)

    def set_monitor(self, monitor) -> None:
        """Attach a health.AnomalyMonitor: every monitored event written
        to the stream is forwarded to it (see event())."""
        self._monitor = monitor

    def fsync(self) -> None:
        """Force the stream through the OS cache — the abort paths call
        this so a postmortem is never truncated by the death it
        documents. Safe on a closed/disabled stream."""
        if self._file is not None:
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
            except OSError:
                pass

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except Exception:
                pass
            self._file = None

    def __enter__(self) -> "RunTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ compilation

    def watcher(self) -> JitWatcher:
        if self._watcher is None:
            self._watcher = JitWatcher(self)
        return self._watcher

    def instrument(self, runtime) -> None:
        """Attach compile observability to a FedRuntime's jitted steps."""
        runtime.set_compile_watcher(self.watcher())

    # --------------------------------------------------------------- records

    def round_event(self, *, rnd: int, epoch: int, lr: float, loss: float,
                    acc: float, n_valid: float,
                    download_bytes: Optional[float],
                    upload_bytes: Optional[float],
                    host_s: float, dispatch_s: float,
                    device_s: float) -> None:
        self.event("round", round=rnd, epoch=epoch, lr=float(lr),
                   loss=float(loss), acc=float(acc), n_valid=float(n_valid),
                   download_bytes=download_bytes, upload_bytes=upload_bytes,
                   host_s=round(host_s, 6), dispatch_s=round(dispatch_s, 6),
                   device_s=round(device_s, 6))

    def epoch_event(self, summary: Dict[str, Any], **extra) -> None:
        """``summary`` is the exact dict the TableLogger receives; its
        presentation keys ("down (MiB)") are normalized for the stream."""
        s = dict(summary)
        self.event("epoch", epoch=int(s.pop("epoch")),
                   lr=float(s.pop("lr")),
                   train_time=float(s.pop("train_time")),
                   train_loss=float(s.pop("train_loss")),
                   train_acc=float(s.pop("train_acc")),
                   test_loss=float(s.pop("test_loss")),
                   test_acc=float(s.pop("test_acc")),
                   download_mib=float(s.pop("down (MiB)")),
                   upload_mib=float(s.pop("up (MiB)")),
                   total_time=float(s.pop("total_time")),
                   **{**s, **extra})

    def memory_event(self, phase: str) -> None:
        """Per-device memory snapshot + derived residency fields (schema
        v6, telemetry/memory_ledger.py): live/peak bytes, peak growth
        since the previous snapshot (which PHASE grew the high-water),
        fragmentation and headroom. Best-effort everywhere: a backend
        without ``memory_stats`` (CPU) degrades every derived field to
        null with a one-time stderr note — the event still records the
        attempt plus the host RSS, so the stream shape is
        backend-independent and null never means zero."""
        if self._file is None:
            return
        import jax

        from commefficient_tpu.telemetry.memory_ledger import \
            ResidencyTracker
        if self._residency is None:
            self._residency = ResidencyTracker()
        devices, derived = self._residency.snapshot(jax.devices())
        rss = None
        try:
            import resource
            rss = (resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                   * 1024)  # linux reports KiB
        except Exception:
            pass
        self.event("memory", phase=phase,
                   devices=[{**d, "stats": _jsonable(d["stats"])
                             if d["stats"] else None} for d in devices],
                   host_rss_bytes=rss, **derived)

    def memory_ledger_event(self, name: str,
                            ledger: Dict[str, Any]) -> None:
        """Static byte inventory of one compiled executable (schema v6,
        telemetry/memory_ledger.py) — emitted by the JitWatcher next to
        each `compile` event, so a buffer-size regression (a de-fusion
        re-materializing per-client d-vectors) shows in every run's
        stream, not only in the dryrun ceilings."""
        from commefficient_tpu.telemetry.memory_ledger import \
            MEMORY_LEDGER_KEYS
        self.event("memory_ledger", name=name,
                   **{k: ledger.get(k) for k in MEMORY_LEDGER_KEYS})

    def nan_abort(self, *, nan_round: int, reason: str, cfg) -> None:
        """The structured replacement for the bare 'TRAINING DIVERGED'
        exit: which round went non-finite, under which mode/clip/sketch
        config, and the last records known finite."""
        self.event("nan_abort", nan_round=int(nan_round), reason=reason,
                   mode=cfg.mode,
                   max_grad_norm=cfg.max_grad_norm,
                   sketch=_sketch_geometry(cfg),
                   last_round=self.last_round,
                   last_epoch=self.last_epoch)

    def _wire_dtype(self) -> Optional[str]:
        """The run's sketch-table wire dtype for the schema-v9 wire
        fields: the resolved --wire_dtype in sketch mode, null for
        cfg-less streams or modes with no table wire."""
        if self.cfg is None or getattr(self.cfg, "mode", None) != "sketch":
            return None
        return (getattr(self.cfg, "wire_dtype", None)
                or getattr(self.cfg, "sketch_dtype", None))

    def bench_event(self, metric: str, result: Dict[str, Any],
                    wire_dtype: Optional[str] = None) -> None:
        self.event("bench", metric=metric, result=result,
                   wire_dtype=wire_dtype or self._wire_dtype())

    def signals_event(self, *, rnd: int, mode: str,
                      signals: Dict[str, Any],
                      download_bytes: Optional[float] = None,
                      upload_bytes: Optional[float] = None,
                      client_download_bytes=None,
                      client_upload_bytes=None) -> None:
        """Compression-signal health for one round (telemetry/signals.py
        computes the dict on device; the driver fetches it at the same
        cadence as the round record). Non-finite values — the NaN used
        for not-applicable signals — serialize as null via _jsonable."""
        self.event("signals", round=rnd, mode=mode, **signals,
                   download_bytes=download_bytes, upload_bytes=upload_bytes,
                   client_download_bytes=client_download_bytes,
                   client_upload_bytes=client_upload_bytes,
                   wire_dtype=self._wire_dtype())

    def layer_signals_event(self, *, rnd: int, mode: str,
                            signal_groups: str, groups, sizes,
                            values: Dict[str, Any]) -> None:
        """Layer-wise compression attribution for one round (schema
        v10, telemetry/layer_signals.py computes the per-group vectors
        on device; the driver fetches them at the signals cadence).
        ``values`` is the layer_signals_to_host dict — None fields and
        NaN entries serialize as nulls, never fake zeros."""
        from commefficient_tpu.telemetry.layer_signals import \
            LAYER_SIGNAL_KEYS
        self.event("layer_signals", round=int(rnd), mode=mode,
                   signal_groups=signal_groups,
                   groups=list(groups), sizes=list(sizes),
                   **{k: values.get(k) for k in LAYER_SIGNAL_KEYS})

    def client_stats_event(self, *, rnd: int, n_participants: int,
                           quantiles: Dict[str, Any],
                           participation: Dict[str, Any]) -> None:
        """Per-client population summary for one round
        (telemetry/clients.py): the device-reduced quantiles joined with
        the host-side participation ledger snapshot — same cadence, same
        host sync as the round record."""
        self.event("client_stats", round=int(rnd),
                   n_participants=int(n_participants),
                   quantiles=quantiles, **participation)

    def population_event(self, *, snapshot: Dict[str, Any]) -> None:
        """Population-scale participation summary (schema v11): the
        ledger's population_snapshot dict — sketch-estimated or exact,
        its ``estimated`` flag says which (telemetry/population.py)."""
        self.event("population", **snapshot)

    def async_round_event(self, *, rec: Dict[str, Any], lr: float,
                          loss: Optional[float] = None,
                          with_device: bool = False) -> None:
        """One async buffered-aggregation commit (core/async_agg.py
        commit record). ``with_device=True`` fetches the record's device
        scalar refs (buffer_n and the post-commit norms) — the caller
        opts in only at the record cadence, because each fetch is a host
        sync; off-cadence commits record their (host-side) staleness
        bookkeeping with the device fields null."""

        def dev(key):
            if not with_device or rec.get(key) is None:
                return None
            import numpy as np
            return float(np.asarray(rec[key]))

        self.event("async_round", round=int(rec["round"]),
                   n_cohorts=int(rec["n_cohorts"]),
                   cohorts=[int(c) for c in rec["cohorts"]],
                   staleness_mean=float(rec["staleness_mean"]),
                   staleness_max=float(rec["staleness_max"]),
                   discount_mean=float(rec["discount_mean"]),
                   discount_min=float(rec["discount_min"]),
                   partial=bool(rec["partial"]),
                   buffer_n=dev("buffer_n"), loss=loss,
                   update_norm=dev("update_norm"),
                   error_norm=dev("error_norm"),
                   velocity_norm=dev("velocity_norm"),
                   lr=float(lr))

    def defense_event(self, *, rnd: int, defense: str, adversary: str,
                      nonfinite_action: str,
                      device: Optional[Dict[str, Any]] = None,
                      quarantine: Optional[Dict[str, Any]] = None,
                      injected: Optional[Dict[str, Any]] = None) -> None:
        """Robustness status of one round (schema v5, core/runtime.py):
        ``device`` is the round's defense scalar dict (already fetched;
        NaN = not-applicable, serialized null), ``quarantine`` the
        QuarantineLedger snapshot, ``injected`` the per-fate injected
        slot counts when fault injection is on."""
        device = device or {}
        q = quarantine or {}
        self.event("defense", round=int(rnd), defense=defense,
                   adversary=adversary, nonfinite_action=nonfinite_action,
                   clip_frac=device.get("clip_frac"),
                   clip_thresh=device.get("clip_thresh"),
                   clipped_mass=device.get("clipped_mass"),
                   trim_frac=device.get("trim_frac"),
                   nonfinite_clients=device.get("nonfinite_clients"),
                   quarantined=int(q.get("quarantined", 0)),
                   ejected=int(q.get("ejected", 0)),
                   quarantine_ids_digest=q.get("quarantine_ids_digest"),
                   injected=injected)

    def alert_event(self, *, rnd: int, rule: str, severity: str,
                    metric: str, value: Optional[float] = None,
                    zscore: Optional[float] = None,
                    median: Optional[float] = None,
                    mad: Optional[float] = None, window: int = 0,
                    action: str = "log") -> None:
        """One anomaly alert (telemetry/health.py normally emits these
        through the monitor; the drivers use this directly for the final
        nonfinite-abort alert so a postmortem's LAST event before the
        nan_abort names the rule that killed the run)."""
        self.event("alert", round=int(rnd), rule=rule, severity=severity,
                   metric=metric, value=value, zscore=zscore, median=median,
                   mad=mad, window=int(window), action=action)

    def fault_event(self, *, rnd: int, kind: str,
                    signal: Optional[str] = None,
                    grace_s: Optional[float] = None,
                    detail: Optional[str] = None,
                    checkpoint: Optional[str] = None) -> None:
        """One run-level fault (schema v8, core/preempt.py): a graceful
        preemption drain, a corrupt-checkpoint fallback at resume, a
        watchdog round_stall, an input-phase retry. Fsynced on write
        (see event()) — a fault record that the fault itself truncates
        would be useless."""
        self.event("fault", round=int(rnd), kind=kind, signal=signal,
                   grace_s=(round(float(grace_s), 3)
                            if grace_s is not None else None),
                   detail=detail, checkpoint=checkpoint)

    def resume_event(self, *, rnd: int, epoch: Optional[int] = None,
                     checkpoint: Optional[str] = None,
                     prior_stream: Optional[str] = None,
                     prior_events: Optional[int] = None) -> None:
        """Crash-recovery lineage record (schema v8). The append-mode
        constructor writes one automatically when it continues an
        existing stream; drivers use this form when the resumed run
        lands in a fresh logdir."""
        self.event("resume", round=int(rnd), epoch=epoch,
                   checkpoint=checkpoint, prior_stream=prior_stream,
                   prior_events=prior_events)

    def span_event(self, tracer) -> None:
        """Drain a tracing.SpanTracer's completed spans into one batched
        ``span`` event. Call OUTSIDE the timed region (the drivers do it
        next to the round record) — the JSONL flush must not land inside
        any phase the spans measure. No-op when nothing happened.
        n_dropped is per-WINDOW (pop_dropped resets the counter), so
        summing it across span events gives the true drop total."""
        dropped = tracer.pop_dropped()
        spans = tracer.drain()
        if not spans and not dropped:
            return
        self.event("span", t0_wall=tracer.t0_wall,
                   n_dropped=int(dropped), spans=spans)

    def collectives_event(self, name: str, ledger) -> None:
        """Collective inventory of one compiled executable — emitted by
        the JitWatcher next to each `compile` event, so a count
        regression (the 32x all_to_all unroll class) shows in every
        run's stream. Schema v9 adds the wire fields: the run's table
        wire dtype and the modeled per-device ICI bytes of the
        table-reduce collectives (null when no device count is known —
        never a fake zero)."""
        from commefficient_tpu.telemetry.collectives import (
            summarize_ledger, table_reduce_wire_bytes)
        table_bytes = None
        try:
            # the wire model needs the COLLECTIVE's participant count:
            # prefer the run's configured mesh size (a 2-device mesh on
            # an 8-device host must model (n-1) = 1, not 7); fall back
            # to the process device count for cfg-less / ad-hoc-mesh
            # streams (the dryrun/scaling arms pin them equal)
            n = 1
            if self.cfg is not None and getattr(self.cfg, "mesh_shape",
                                                ()):
                for dim in self.cfg.mesh_shape:
                    n *= int(dim)
            else:
                import jax
                n = len(jax.devices())
            if n > 1:
                table_bytes = table_reduce_wire_bytes(ledger, n)
        except Exception:
            pass
        self.event("collectives", name=name, **summarize_ledger(ledger),
                   wire_dtype=self._wire_dtype(),
                   table_reduce_bytes=table_bytes)

    def write_summary(self, *, aborted: bool, n_rounds: int,
                      total_download_mib: Optional[float] = None,
                      total_upload_mib: Optional[float] = None,
                      final: Optional[Dict[str, Any]] = None) -> None:
        self.event("summary", run_type=self.run_type, aborted=aborted,
                   n_rounds=int(n_rounds),
                   total_download_mib=total_download_mib,
                   total_upload_mib=total_upload_mib,
                   wall_time_s=round(time.perf_counter() - self._t0, 3),
                   event_counts=dict(self._counts),
                   final=final)


def maybe_create(cfg, run_type: str, logdir: Optional[str] = None,
                 resume_info: Optional[Dict[str, Any]] = None
                 ) -> Optional[RunTelemetry]:
    """Driver entry point: honor --no_telemetry, default the logdir to
    the run's ``make_logdir`` location, announce the path on stderr
    (stdout belongs to the byte-stable console loggers).
    ``resume_info`` ({round, epoch, checkpoint}) threads the restored
    position into the stream's `resume` lineage record."""
    if not getattr(cfg, "telemetry", True):
        return None
    if logdir is None:
        from commefficient_tpu.utils import make_logdir
        logdir = make_logdir(cfg)
    tel = RunTelemetry(logdir, run_type, cfg=cfg, resume_info=resume_info)
    if not tel.active:
        # the constructor already warned; do not announce (or hand the
        # caller) a stream that was never created
        return None
    print(f"telemetry: {tel.path}", file=sys.stderr)
    return tel
