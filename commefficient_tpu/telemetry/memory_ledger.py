"""HBM memory ledger + live-residency accounting: prove where the bytes go.

Two complementary instruments, both host-side-only (zero hot-path cost,
nothing here ever enters a jitted computation):

**Ledger** — per-executable STATIC byte accounting from XLA's
``compiled.memory_analysis()`` (``CompiledMemoryStats``): temp buffers,
argument/output/alias and generated-code bytes. The ``JitWatcher``
records it on every compile of a watched executable and emits a
schema-v6 ``memory_ledger`` event next to the ``compile`` event, so a
buffer-size regression (a fusion break materializing a ``(W, d)``
per-client gradient, the dense ``(d,)`` f32 gradient the sketch round
still pays — ~2.9 GB at GPT-2 124M) shows in every run's stream and is
asserted as hard per-executable byte ceilings by
``__graft_entry__.dryrun_multichip``.

**Residency** — per-phase DYNAMIC allocator tracking from
``device.memory_stats()``: live bytes, allocator high-water peak, the
peak's growth since the previous snapshot (which phase grew the
high-water: rounds vs validation vs checkpoint), fragmentation
(peak - live) and the headroom fraction against the device limit — the
near-OOM precursor ``telemetry/health.py``'s ``hbm_pressure`` rule
watches so the flight recorder arms BEFORE the allocator dies.
Backends without ``memory_stats`` (the CPU container) degrade to null
fields with a one-time stderr note — never fake zeros, never a crash.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

# byte fields of one ``memory_ledger`` event (beyond the executable
# name). ``total_bytes`` = argument + output + temp + generated-code —
# the executable's whole static footprint (aliased bytes are a subset
# of argument/output, counted once). scripts/teleview.py mirrors these
# as literals for jax-free analysis; tests/test_memory.py pins them.
MEMORY_LEDGER_KEYS = ("temp_bytes", "argument_bytes", "output_bytes",
                      "alias_bytes", "generated_code_bytes", "total_bytes")

# derived residency fields of the enriched (schema v6) ``memory`` event;
# every one is null when the backend reports no allocator stats
MEMORY_KEYS = ("live_bytes", "peak_bytes", "delta_peak_bytes",
               "fragmentation_bytes", "limit_bytes", "headroom_frac")

# The acceptance gate ROADMAP item 1's encode-fusion work committed to
# flip (PR 8 staged it False): the sketch-mode round used to
# MATERIALIZE the dense (d,) f32 aggregated gradient before encoding it
# (temp_bytes >= d*4 — the structural HBM suspect behind the flat GPT-2
# MFU). With the fused encode (core/client.py make_forward_grad /
# make_fused_grad: the microbatch scan carries the (r, c) sketch table,
# --sketch_fused_encode) the dense gradient never exists, so the
# dryrun_multichip sketch gate now asserts the INVERSE: temp_bytes <
# d*4 — a regression that re-materializes the dense aggregate fails the
# dryrun. check_dense_grad_floor(fused=False) keeps the pre-fusion
# direction testable (and gates the explicit --sketch_fused_encode off
# arm).
SKETCH_ENCODE_FUSED = True

# attribute name on the CompiledMemoryStats object -> ledger field
_STATS_ATTRS = {
    "temp_size_in_bytes": "temp_bytes",
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "alias_size_in_bytes": "alias_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
}


def ledger_from_stats(stats: Any) -> Optional[Dict[str, Any]]:
    """Parse a ``CompiledMemoryStats``-shaped object (attribute access,
    so tests can drive it with a stub) into the ledger dict. Returns
    None when the object exposes NO recognizable byte field — an
    unknown-shape result must yield no event, not an all-null one."""
    out: Dict[str, Any] = {k: None for k in MEMORY_LEDGER_KEYS}
    found = False
    for attr, key in _STATS_ATTRS.items():
        v = getattr(stats, attr, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = int(v)
            found = True
    if not found:
        return None
    parts = [out[k] for k in ("argument_bytes", "output_bytes",
                              "temp_bytes", "generated_code_bytes")]
    if any(p is not None for p in parts):
        out["total_bytes"] = int(sum(p for p in parts if p is not None))
    return out


def ledger_from_compiled(compiled) -> Optional[Dict[str, Any]]:
    """Ledger of a ``lowered.compile()`` result. Best-effort like every
    observability path: a backend without ``memory_analysis`` (or one
    that raises) yields None rather than an exception."""
    try:
        return ledger_from_stats(compiled.memory_analysis())
    except Exception:
        return None


def round_memory_ledger(runtime, state, client_ids, batch, mask,
                        lr: float = 0.1) -> Optional[Dict[str, Any]]:
    """Lower+compile the runtime's round step on the given arguments and
    return its memory ledger — the dryrun/test entry point (the
    telemetry path instead hooks the JitWatcher's compile), mirroring
    ``collectives.round_ledger``."""
    import jax.numpy as jnp
    lowered = runtime._round.lower(
        state, client_ids, batch, mask,
        jnp.asarray(lr, jnp.float32), runtime.cs,
        getattr(runtime, "_gid", None))
    return ledger_from_compiled(lowered.compile())


# ------------------------------------------------------------------ ceilings


def check_ceilings(ledger: Optional[Dict[str, Any]],
                   ceilings: Dict[str, float]) -> List[str]:
    """Hard byte-ceiling gate over one ledger: every ceiled field must be
    PRESENT and within its ceiling. A null field fails too — a gate that
    silently passes when the measurement vanished proves nothing (the
    collective-ledger lesson: absence of evidence read as health)."""
    problems: List[str] = []
    if ledger is None:
        return [f"no memory ledger (memory_analysis unavailable) but "
                f"ceilings were asserted: {sorted(ceilings)}"]
    for key, limit in sorted(ceilings.items()):
        v = ledger.get(key)
        if v is None:
            problems.append(f"{key} is null (cannot prove <= {limit:.0f})")
        elif v > limit:
            problems.append(f"{key} {v} exceeds ceiling {limit:.0f}")
    return problems


def check_dense_grad_floor(ledger: Optional[Dict[str, Any]], d: int,
                           fused: bool = SKETCH_ENCODE_FUSED) -> List[str]:
    """The sketch-mode dense-gradient gate (see SKETCH_ENCODE_FUSED):
    un-fused, the round's temp buffers must CONTAIN the dense (d,) f32
    aggregated gradient (temp >= d*4 — documenting today's cost);
    fused, they must NOT (temp < d*4 — the fusion PR's acceptance
    proof). Returns a problems list, empty = the expected regime."""
    if ledger is None or ledger.get("temp_bytes") is None:
        return ["temp_bytes is null (cannot check the dense-gradient "
                "floor)"]
    temp, floor = int(ledger["temp_bytes"]), int(d) * 4
    if not fused and temp < floor:
        return [f"temp_bytes {temp} < d*4 = {floor}: the dense gradient "
                "no longer materializes — flip SKETCH_ENCODE_FUSED and "
                "invert this gate (the item-1 fusion acceptance)"]
    if fused and temp >= floor:
        return [f"temp_bytes {temp} >= d*4 = {floor}: SKETCH_ENCODE_FUSED "
                "claims the encode is fused into the accumulator scan, "
                "but the round still materializes a dense-gradient-sized "
                "temp buffer"]
    return []


def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            total += int(size) * int(itemsize)
    return total


def round_memory_ceilings(runtime, state, batch) -> Dict[str, float]:
    """Per-executable byte ceilings for ONE federated round, computed
    from the run's own geometry so the gate scales from the dryrun's
    tiny shapes to real models:

    - ``argument_bytes``: the state + batch trees the round actually
      takes (everything else — ids/mask/lr/sketch constants — rides in
      the slack term);
    - ``output_bytes``: the new state + metrics (metrics are O(W) + a
      handful of diagnostics; state dominates);
    - ``temp_bytes``: the round's legitimate working set — per-client
      activation traffic (a multiple of the batch bytes), the dense
      federated vectors (client gradients aggregate through O(1) d-sized
      buffers since the fused-clients change — a per-client (W, d)
      materialization blows through this, which is the point), and the
      sketch tables.

    The multipliers carry measured headroom (CPU XLA on the dryrun
    shapes sits at roughly half of each ceiling); the regression class
    this gate exists to catch — a de-fusion re-materializing per-client
    d-vectors — scales with W and overshoots by the client count."""
    d_pad = int(runtime.d_pad)
    cfg = runtime.cfg
    table = int(cfg.num_rows) * int(cfg.num_cols)
    state_bytes = _tree_bytes(state)
    batch_bytes = _tree_bytes(batch)
    slack = 16 * 2**20  # constants, control scalars, codegen rounding
    return {
        "argument_bytes": 1.25 * (state_bytes + batch_bytes) + slack,
        "output_bytes": 1.25 * state_bytes + batch_bytes + slack,
        # activations: <= 48x the batch bytes live at once (ResNet-scale
        # forward+backward per microbatch); dense vectors: <= 8 d-sized
        # f32 buffers (grad, velocity, error, update + transient pairs);
        # tables: <= 8 copies (encode/decode + transposes)
        "temp_bytes": (48.0 * batch_bytes + 8.0 * 4 * d_pad
                       + 8.0 * 4 * table + slack),
    }


# ----------------------------------------------------------------- residency


def residency_fields(device_stats: List[Optional[Dict[str, Any]]],
                     prev_peak: Optional[float] = None) -> Dict[str, Any]:
    """Derive the MEMORY_KEYS residency fields from a list of per-device
    ``memory_stats()`` dicts (None / empty for devices that report
    nothing). Aggregation is worst-device over reporting devices — the
    binding constraint on a replicated-state mesh is the worst device —
    and the DERIVED fields (fragmentation, headroom) are computed
    per-device BEFORE aggregating, so they always describe a real
    device: max live/peak paired with an independently-maxed limit
    would overstate the headroom of a small-limit device about to OOM.
    Every field is null when no device reports — never a fake zero."""
    def _num(s, key):
        v = s.get(key) if isinstance(s, dict) else None
        return v if isinstance(v, (int, float)) else None

    lives, peaks, limits, frags, headrooms = [], [], [], [], []
    for s in device_stats:
        live, peak, limit = (_num(s, "bytes_in_use"),
                             _num(s, "peak_bytes_in_use"),
                             _num(s, "bytes_limit"))
        if live is not None:
            lives.append(live)
        if peak is not None:
            peaks.append(peak)
        if limit is not None:
            limits.append(limit)
        if peak is not None and live is not None:
            frags.append(peak - live)
        if limit and peak is not None:
            headrooms.append((limit - peak) / limit)
    peak = max(peaks) if peaks else None
    out: Dict[str, Any] = {
        "live_bytes": max(lives) if lives else None,
        "peak_bytes": peak,
        "delta_peak_bytes": (peak - prev_peak
                             if peak is not None and prev_peak is not None
                             else None),
        "fragmentation_bytes": max(frags) if frags else None,
        "limit_bytes": max(limits) if limits else None,
        "headroom_frac": (round(min(headrooms), 6)
                          if headrooms else None),
    }
    return out


class ResidencyTracker:
    """Owns the snapshot-to-snapshot state of the residency fields (the
    previous peak for delta attribution) and the one-time degradation
    note for backends without ``memory_stats``.

    ``snapshot(devices)`` returns ``(device_records, derived_fields)``
    ready for the ``memory`` event: per-device ``{id, kind, stats}``
    (stats null when unavailable) plus the MEMORY_KEYS fields. A device
    whose ``memory_stats`` method is missing, raises, or returns an
    empty dict degrades to null — the stream shape stays
    backend-independent and the degradation is announced ONCE."""

    def __init__(self):
        self._prev_peak: Optional[float] = None
        self._warned = False

    def snapshot(self, devices) -> tuple:
        records, stats_list = [], []
        for d in devices:
            stats = None
            try:
                getter = getattr(d, "memory_stats", None)
                if getter is not None:
                    stats = getter()
            except Exception:
                stats = None
            if not stats:          # missing method, raised, or empty dict
                stats = None
            records.append({"id": int(getattr(d, "id", 0)),
                            "kind": getattr(d, "device_kind", "unknown"),
                            "stats": stats})
            stats_list.append(stats)
        derived = residency_fields(stats_list, self._prev_peak)
        if derived["peak_bytes"] is not None:
            self._prev_peak = derived["peak_bytes"]
        # the degradation note fires only on FULL absence — a backend
        # exposing partial stats (live but no peak) keeps its non-null
        # fields and must not be announced as "unavailable"
        if (not self._warned and devices
                and all(derived[k] is None for k in MEMORY_KEYS)):
            self._warned = True
            print("NOTE: device memory_stats() unavailable/empty on this "
                  "backend; memory-event residency fields (live/peak/"
                  "fragmentation/headroom) will be null — null means "
                  "'not measurable here', never zero", file=sys.stderr)
        return records, derived
