"""Compile observability for the jitted round/val steps.

``JitWatcher.wrap(name, fn)`` returns a callable that manages its own
AOT cache keyed on the argument signature (treedef + leaf shape/dtype).
The first call with a new signature runs ``fn.lower`` and ``.compile()``
under split wall timers and logs a ``compile`` event carrying the XLA
``cost_analysis()`` FLOPs / bytes-accessed — so a RECOMPILE (a shape
change, a donation miss materializing a new layout) shows up as a
second ``compile`` event for the same name instead of a silent
multi-second (or, at GPT-2 scale, multi-minute) stall. Subsequent calls
dispatch straight to the cached compiled executable, bypassing jit's
own re-trace.

Never trades correctness for observability: any failure in the AOT path
(an input the signature key cannot describe, an executable rejecting an
aval/sharding the plain jit path would accept) permanently drops the
wrapper into pass-through mode for that function, logging one final
``compile`` event with ``fallback: true``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict

import jax


def _signature(args) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(
        (tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)))
        for leaf in leaves))


def _cost_analysis(compiled) -> Dict[str, Any]:
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost) if cost else {}
    except Exception:
        return {}


class JitWatcher:
    """Wraps jitted callables; reports compiles to a RunTelemetry."""

    def __init__(self, telemetry):
        self._telemetry = telemetry
        self.n_compiles = 0
        # latest cost-analysis FLOPs per watched name (None when XLA
        # returned no count) — the MFU numerator utilization.py joins
        # with the round's wall time; a recompile overwrites, so the
        # count always describes the executable that is actually running
        self.flops: Dict[str, Any] = {}
        # latest cost-analysis bytes-accessed per watched name — the
        # roofline denominator (arithmetic intensity = flops / bytes);
        # same overwrite-on-recompile semantics as `flops`
        self.bytes: Dict[str, Any] = {}
        # latest memory_analysis ledger per watched name (memory_ledger
        # .py) — the per-executable static byte inventory; the flight
        # recorder ships the aborting executable's entry in memory.json
        self.memory: Dict[str, Dict[str, Any]] = {}

    def wrap(self, name: str, fn: Callable) -> Callable:
        cache: Dict[Any, Any] = {}
        state = {"fallback": False}

        def emit(n, lower_s, compile_s, cost, fallback=False):
            self.n_compiles += 1
            if cost.get("flops"):
                self.flops[name] = cost.get("flops")
            if cost.get("bytes accessed"):
                self.bytes[name] = cost.get("bytes accessed")
            self._telemetry.event(
                "compile", name=name, n_compiles=n,
                lower_s=round(lower_s, 6), compile_s=round(compile_s, 6),
                flops=cost.get("flops"),
                bytes_accessed=cost.get("bytes accessed"),
                fallback=fallback)

        def wrapped(*args):
            if state["fallback"]:
                return fn(*args)
            try:
                key = _signature(args)
            except Exception:
                state["fallback"] = True
                emit(len(cache), 0.0, 0.0, {}, fallback=True)
                return fn(*args)
            compiled = cache.get(key)
            if compiled is None:
                try:
                    t0 = time.perf_counter()
                    lowered = fn.lower(*args)
                    t1 = time.perf_counter()
                    compiled = lowered.compile()
                    t2 = time.perf_counter()
                except Exception:
                    # un-lowerable input (or an AOT-unsupported transform
                    # nesting): give up on observation, keep the run alive
                    state["fallback"] = True
                    emit(len(cache), 0.0, 0.0, {}, fallback=True)
                    return fn(*args)
                cache[key] = compiled
                emit(len(cache), t1 - t0, t2 - t1,
                     _cost_analysis(compiled))
                # collective ledger of the fresh executable (count/kind/
                # bytes of every cross-device collective) — best-effort,
                # like every observability path here
                if hasattr(self._telemetry, "collectives_event"):
                    try:
                        from commefficient_tpu.telemetry.collectives import \
                            ledger_from_compiled
                        self._telemetry.collectives_event(
                            name, ledger_from_compiled(compiled))
                    except Exception:
                        pass
                # memory ledger of the fresh executable (memory_analysis
                # temp/argument/output/alias/generated-code bytes) —
                # the per-executable HBM inventory, emitted next to the
                # compile event like the collectives; a backend without
                # memory_analysis yields no event, not an all-null one
                if hasattr(self._telemetry, "memory_ledger_event"):
                    try:
                        from commefficient_tpu.telemetry.memory_ledger \
                            import ledger_from_compiled as _mem_ledger
                        mledger = _mem_ledger(compiled)
                        if mledger is not None:
                            self.memory[name] = mledger
                            self._telemetry.memory_ledger_event(
                                name, mledger)
                    except Exception:
                        pass
            try:
                return compiled(*args)
            except Exception:
                # AOT executables validate input avals/shardings more
                # strictly than jit dispatch; if this signature's inputs
                # slip past our key but not the executable, never risk the
                # run — pass through to the plain jit path from here on.
                state["fallback"] = True
                emit(len(cache), 0.0, 0.0, {}, fallback=True)
                # ONLY retry when the inputs are still alive: a failure
                # DURING execution (OOM at scale) may already have
                # consumed donated buffers, and retrying with deleted
                # arrays would bury the real error under a confusing
                # "Array has been deleted" — re-raise the original then.
                if any(getattr(leaf, "is_deleted", lambda: False)()
                       for leaf in jax.tree_util.tree_leaves(args)):
                    raise
                return fn(*args)

        wrapped.__name__ = f"watched_{name}"
        return wrapped
