"""Compression-signal health diagnostics, computed INSIDE the jitted round.

FetchSGD's claim lives inside the compressed channel — count-sketch
collision noise, error-feedback accumulator growth, heavy-hitter
recovery quality — and round 5 proved those quantities can diverge for
dozens of rounds while the loss still prints finite numbers
(runs/gpt2_conv/README.md: subtract-EF arms died at round 7-29 with no
earlier signal). Everything here is cheap on-device reductions appended
to the round step's metrics pytree: no host sync in the hot path — the
scalars ride the same async fetch as the loss, at the driver's existing
telemetry cadence.

The signal set (all float32 scalars; NaN = not applicable for this
mode/topology, serialized as JSON null):

- ``grad_norm``        : L2 of the aggregated transmitted quantity in
                         its own space (dense L2, or table Frobenius)
- ``grad_true_norm``   : L2 of the dense aggregated gradient where it
                         exists (dense modes, sketch deferred-encode on
                         one device, dense pre-image server state)
- ``grad_l2estimate``  : sketch-mode ``cs.l2estimate`` of the
                         aggregated table — its gap to grad_true_norm
                         is the collision-noise proxy (EF-SGD's
                         convergence constant is governed by exactly
                         this ratio)
- ``velocity_norm`` / ``error_norm``: L2/Frobenius of the NEW server
                         Vvelocity/Verror — the EF-growth signal
                         (Karimireddy et al.: bounded error norm is the
                         whole convergence argument)
- ``error_l2estimate`` : table-space Verror's estimated pre-image norm
- ``update_norm``      : L2 of the applied weight update (true d)
- ``support_density``  : nnz(update)/d — k-sparsity health (a dense
                         mode reads ~1.0, sketch/top-k ~k/d)
- ``topk_overlap``     : |support(update) ∩ exact-top-k(dense error)|/k
                         — heavy-hitter recovery quality. Needs a dense
                         error reference, so it is gated behind
                         ``--signals_exact``: free where the server
                         already holds a dense error (true_topk, sketch
                         dense pre-image — there it also measures
                         approx_topk recall), and for table-state
                         sketch it maintains a dense SHADOW error
                         accumulator (sig_Vvelocity/sig_Verror on
                         FedState; single-device deferred-encode only,
                         since only there does the dense summed
                         gradient exist).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

SIGNAL_KEYS = (
    "grad_norm", "grad_true_norm", "grad_l2estimate",
    "velocity_norm", "error_norm", "error_l2estimate",
    "update_norm", "support_density", "topk_overlap",
)


def _l2(x: jax.Array) -> jax.Array:
    # vdot+sqrt instead of jnp.linalg.norm: stays a single fused
    # reduction for 2-D tables too (Frobenius), and on a mesh lowers to
    # a per-shard partial + scalar psum rather than an all-gather
    return jnp.sqrt(jnp.vdot(x, x)).astype(jnp.float32)


def _topk_overlap(update: jax.Array, dense_err: jax.Array,
                  k: int) -> jax.Array:
    """Fraction of the exact top-k coordinates of ``dense_err`` (by
    magnitude) that the update's support recovered. O(k) gather after
    the top-k select — the select itself is the only O(d) cost."""
    _, idx = jax.lax.top_k(dense_err * dense_err, k)
    return jnp.mean((update[idx] != 0).astype(jnp.float32))


def round_signals(
    cfg,
    *,
    agg: jax.Array,
    update: jax.Array,
    Vvel_prev: jax.Array,
    Verr_prev: jax.Array,
    Vvel_new: jax.Array,
    Verr_new: jax.Array,
    cs=None,
    dense_agg: Optional[jax.Array] = None,
    sig_vel: Optional[jax.Array] = None,
    sig_err: Optional[jax.Array] = None,
) -> Tuple[Dict[str, jax.Array], Optional[jax.Array], Optional[jax.Array]]:
    """Compute the round's signal dict (traced inside the round step).

    ``agg``/``update`` are the server_update input/output exactly as the
    runtime holds them (update pre-padding; true-d for sketch decode,
    padded-dense otherwise — padding coordinates are identically zero so
    the norms are unaffected and only support_density needs the true-d
    slice). ``dense_agg`` is the dense aggregated gradient where one
    exists outside the transmitted space (sketch deferred encode).
    ``sig_vel``/``sig_err`` are the dense shadow accumulators (or None);
    returns their updated values so the runtime can thread them through
    FedState.
    """
    d = cfg.grad_size
    nan = jnp.full((), jnp.nan, jnp.float32)
    upd_t = update[:d] if update.ndim == 1 else update

    sig: Dict[str, jax.Array] = {}
    sig["update_norm"] = _l2(upd_t)
    sig["support_density"] = jnp.mean((upd_t != 0).astype(jnp.float32))
    sig["velocity_norm"] = _l2(Vvel_new)
    sig["error_norm"] = _l2(Verr_new)
    sig["grad_norm"] = _l2(agg)

    is_table = agg.ndim == 2
    if is_table:
        sig["grad_l2estimate"] = cs.l2estimate(agg).astype(jnp.float32)
        sig["error_l2estimate"] = cs.l2estimate(Verr_new).astype(jnp.float32)
        sig["grad_true_norm"] = (_l2(dense_agg) if dense_agg is not None
                                 else nan)
    else:
        sig["grad_l2estimate"] = nan
        sig["error_l2estimate"] = nan
        # dense transmitted space: the aggregate IS the dense gradient
        sig["grad_true_norm"] = sig["grad_norm"]

    overlap = nan
    new_sig_vel, new_sig_err = sig_vel, sig_err
    if getattr(cfg, "signals_exact", False):
        rho = cfg.virtual_momentum
        if sig_err is not None:
            # table-state sketch: dense shadow EF replicating what an
            # exact-state server would hold (the dense_preimage rule
            # without the enc+dec round-trip): its pre-feedback error is
            # the dense reference the sketch's top-k tries to recover
            shadow_vel = dense_agg + rho * sig_vel
            err_pre = sig_err + shadow_vel
            overlap = _topk_overlap(upd_t, err_pre, cfg.k)
            supp = upd_t != 0
            new_sig_err = jnp.where(supp, 0.0, err_pre)
            new_sig_vel = jnp.where(supp, 0.0, shadow_vel)
            if cfg.error_decay < 1.0:
                new_sig_err = cfg.error_decay * new_sig_err
        elif cfg.mode == "true_topk" or (cfg.mode == "sketch"
                                         and not is_table):
            # the server's own error is already dense: reconstruct its
            # pre-feedback value from the previous state (the new state
            # is post-zeroing, which would make the overlap vacuous).
            # true_topk reads ~1.0 by construction unless --approx_topk
            # (then it measures the approximate select's recall);
            # dense-preimage sketch measures recovery through the
            # enc+dec round-trip.
            err_pre = (Verr_prev + agg + rho * Vvel_prev)[: upd_t.shape[0]]
            overlap = _topk_overlap(upd_t, err_pre, cfg.k)
    sig["topk_overlap"] = overlap
    return sig, new_sig_vel, new_sig_err


def signals_to_host(signals: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """Fetch a metrics['signals'] dict to plain floats for the telemetry
    event (the caller has already synced the metrics pytree)."""
    import numpy as np
    if not signals:
        return {}
    return {k: float(np.asarray(v)) for k, v in signals.items()}
