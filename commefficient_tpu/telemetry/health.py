"""Online anomaly detection over the telemetry streams, and the flight
recorder that snapshots state before a run dies.

PRs 1-3 RECORD everything — round losses, compression-signal norms, MFU,
and now per-client population quantiles — but nothing WATCHES the
recordings: round 5's measured-divergent regimes (the subtract-EF arms,
the local_topk leak) drifted for dozens of rounds with their error norms
growing in plain sight, and the device-side ``nan_round`` flag only
fires *after* the state is poisoned. :class:`AnomalyMonitor` closes the
loop: it keeps a rolling median/MAD history of a small set of watched
stream fields and raises a schema-v3 ``alert`` event when a robust
z-score leaves the envelope — loss spikes, EF-accumulator blowups,
heavy-hitter recovery collapse, MFU cliffs, client-population loss
spread — plus nonfinite-precursor rules (a watched metric that WAS
numeric turning null is the last observable event before the abort).

Median/MAD (not mean/std) on purpose: the history will CONTAIN the
anomalies it is trying to flag, and a single spike must not drag the
envelope after it. The MAD is floored at 2% of |median| so quantized
metrics (rounded MFU) cannot fire on noise.

``--alert_action`` escalates what a fired rule does:

- ``log``        — the alert event only (always written);
- ``warn``       — + one stderr line;
- ``checkpoint`` — + the :class:`FlightRecorder` writes a ONE-SHOT
  postmortem bundle on the first firing: the live ``FedState`` through
  the existing checkpoint layer, the last-N telemetry events, and the
  alert context — so the round that *precedes* a NaN is preserved for
  replay instead of dying with the process;
- ``abort``      — + the driver stops training (summary records
  ``aborted=True``), mirroring the NaN abort.

Feeding is wired through ``RunTelemetry.set_monitor``: every monitored
event the stream writes is forwarded here after serialization, so the
monitor sees exactly what a postmortem reader will see (NaN already
null). Dependency-free (no jax/numpy in the detection path) — the same
rules run identically under ``teleview alerts --replay`` on a machine
without jax.
"""

from __future__ import annotations

import json
import math
import os
import sys
from collections import deque
from typing import Any, Dict, List, Optional

SEVERITIES = ("info", "warn", "critical")
# event kinds RunTelemetry forwards to an attached monitor
MONITORED_KINDS = ("round", "signals", "utilization", "client_stats",
                   "async_round", "defense", "memory", "layer_signals",
                   "population")

# coverage_stall: consecutive population events with no distinct-
# participant growth (while rounds advance and the universe is not yet
# covered) before the rule fires — shared with `teleview diff
# --coverage_stall`
COVERAGE_STALL_WINDOW = 5

# The rule table: each rule watches ONE field of ONE event kind.
# kind="z" fires on a robust z-score breach of the rolling history
# (direction high/low); kind="nonfinite" fires when a field that has
# numeric history arrives null (the nonfinite-precursor counter —
# fields that are null because they are N/A for the mode never fire,
# since they never build numeric history).
RULES = (
    dict(name="loss_spike", event="round", field="loss",
         kind="z", direction="high", severity="warn"),
    dict(name="loss_nonfinite", event="round", field="loss",
         kind="nonfinite", severity="critical"),
    dict(name="grad_norm_spike", event="signals", field="grad_norm",
         kind="z", direction="high", severity="warn"),
    dict(name="error_norm_blowup", event="signals", field="error_norm",
         kind="z", direction="high", severity="critical"),
    dict(name="velocity_norm_blowup", event="signals",
         field="velocity_norm", kind="z", direction="high",
         severity="critical"),
    dict(name="update_nonfinite", event="signals", field="update_norm",
         kind="nonfinite", severity="critical"),
    dict(name="topk_overlap_collapse", event="signals",
         field="topk_overlap", kind="z", direction="low", severity="warn"),
    dict(name="mfu_cliff", event="utilization", field="mfu",
         kind="z", direction="low", severity="warn"),
    dict(name="input_starvation", event="utilization",
         field="input_wait_frac", kind="z", direction="high",
         severity="info"),
    dict(name="client_loss_spread", event="client_stats",
         field="loss_spread", kind="z", direction="high", severity="warn"),
    # async buffered aggregation (core/async_agg.py, schema v4): the
    # staleness-induced EF-divergence precursor — stale discounted
    # cohorts leaking into the virtual error accumulator show up as
    # error_norm growth at COMMIT granularity rounds before the loss
    # goes non-finite (the same failure shape as the sync EF blowups,
    # observed on the async_round stream instead of signals)
    dict(name="async_ef_blowup", event="async_round", field="error_norm",
         kind="z", direction="high", severity="critical"),
    dict(name="async_loss_spike", event="async_round", field="loss",
         kind="z", direction="high", severity="warn"),
    dict(name="staleness_spike", event="async_round",
         field="staleness_max", kind="z", direction="high",
         severity="info", mad_floor_abs=0.5),
    # robustness subsystem (schema v5, core/runtime.py defense events +
    # the client_stats tx_norm quantiles): a client whose transmitted
    # update norm leaves the population envelope is the boosted/scale-
    # attack signature BEFORE any defense decision; quarantine count
    # growth is the broken-fleet signature. The count-like quarantined
    # metric sits at a constant zero on healthy runs, so it carries the
    # absolute MAD floor (see robust_z): a single benched client above
    # an all-zero history is the system WORKING, not an anomaly — a
    # multi-client jump still fires. tx_norm_max is scale-dependent
    # (model/lr set its magnitude), so no fixed absolute floor fits;
    # its healthy history has a nonzero median and the 2%-of-median
    # relative floor does the quieting instead.
    dict(name="update_norm_outlier", event="client_stats",
         field="tx_norm_max", kind="z", direction="high",
         severity="warn"),
    dict(name="quarantine_growth", event="defense", field="quarantined",
         kind="z", direction="high", severity="warn",
         mad_floor_abs=0.5),
    # HBM pressure (schema v6 memory events, telemetry/memory_ledger
    # .py): the allocator high-water peak leaving its rolling envelope
    # is the near-OOM precursor — a leak (an accidentally retained
    # state copy, a growing host->device staging buffer) shows up as
    # anomalous peak growth SNAPSHOTS before the allocator dies, which
    # is when a flight-recorder bundle can still be written. A healthy
    # run's peak is a near-constant after warm-up, so the relative MAD
    # floor would be 2% of multi-GB = tens of MB of tolerated jitter
    # already; the absolute floor (16 MiB) only guards tiny-model runs
    # whose whole peak is smaller than allocator rounding.
    dict(name="hbm_pressure", event="memory", field="peak_bytes",
         kind="z", direction="high", severity="warn",
         mad_floor_abs=16 * 2**20),
    # layer-wise compression attribution (schema v10, telemetry/
    # layer_signals.py): the STARVATION signature — a parameter group
    # holding a material share of the round's dense gradient energy
    # while winning (almost) none of the k top-k coordinates, for a
    # window of consecutive observations. This is the FetchSGD-lineage
    # per-layer failure mode at high compression: small-mass layers
    # lose the global top-k race and their signal rots in error
    # feedback. kind="starvation" is evaluated per GROUP (not a scalar
    # z-score) with the thresholds/window shared with teleview layers
    # (layer_signals.STARVATION_*); silent when grad_mass is null
    # (fused-encode / mesh sketch rounds) — starvation is measured
    # against gradient mass, never guessed from the update side.
    dict(name="group_starvation", event="layer_signals", field="topk_count",
         kind="starvation", severity="warn"),
    # population-scale observability (schema v11, telemetry/
    # population.py): coverage_stall — distinct-participant growth
    # flatlining across COVERAGE_STALL_WINDOW consecutive population
    # events while rounds advance and the universe is not yet covered
    # (a stuck sampler shard at 10^6 clients looks exactly like healthy
    # training on every OTHER stream); hh_churn — the most-sampled
    # heavy-hitter set turning over anomalously fast (robust z on the
    # Jaccard turnover between consecutive top_sampled sets — a churn
    # burst is the drifted-sampler / hijacked-cohort signature). The
    # absolute MAD floor keeps single-slot rotation in an otherwise
    # stable set (turnover ~0.1 over a 10-entry list) from firing on a
    # constant-zero history.
    dict(name="coverage_stall", event="population", field="distinct",
         kind="coverage_stall", severity="warn"),
    dict(name="hh_churn", event="population", field="top_sampled",
         kind="hh_churn", severity="warn", mad_floor_abs=0.05),
)


def _extract(rule: Dict[str, Any], fields: Dict[str, Any]) -> Any:
    """Pull the watched value out of one event's fields. Derived metric:
    ``client_stats.loss_spread`` = p95 - p5 of the per-client loss
    quantiles (the population-divergence signal)."""
    if rule["event"] == "client_stats" and rule["field"] == "loss_spread":
        q = (fields.get("quantiles") or {}).get("loss") or {}
        hi, lo = q.get("p95"), q.get("p5")
        if isinstance(hi, (int, float)) and isinstance(lo, (int, float)):
            return float(hi) - float(lo)
        return None
    if rule["event"] == "client_stats" and rule["field"] == "tx_norm_max":
        # the update_norm_outlier feed: the round's largest per-client
        # transmitted-update norm (the boosted-client signature)
        q = (fields.get("quantiles") or {}).get("tx_norm") or {}
        v = q.get("max")
        return float(v) if isinstance(v, (int, float)) else None
    return fields.get(rule["field"])


def robust_z(value: float, history: List[float],
             mad_floor_frac: float = 0.02,
             mad_floor_abs: float = 0.0) -> Dict[str, float]:
    """Median/MAD z-score of ``value`` against ``history`` (the standard
    0.6745 normal-consistency factor, so z compares to sigma units).

    The MAD is floored at ``mad_floor_frac * |median|`` so a constant or
    quantized history cannot make every deviation infinite — but that
    relative floor is itself ZERO when the rolling median is zero (e.g.
    staleness on a no-latency run, quarantine counts on a healthy
    fleet), and the old 1e-12 backstop made the FIRST nonzero tick fire
    with an astronomical z. ``mad_floor_abs`` is the fix: an absolute
    epsilon floor, supplied per rule for metrics whose healthy state is
    a constant zero in natural units of ~1 (a floor of 0.5 keeps a
    single-unit tick below z = 1.35 while a jump of several units still
    breaches the default threshold 6). It defaults to 0 so continuous
    metrics with real scatter (loss, mfu) keep their full sensitivity.
    Regression-tested on a constant-zero-then-tick history in
    tests/test_health.py."""
    xs = sorted(history)
    n = len(xs)
    med = (xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
    dev = sorted(abs(x - med) for x in xs)
    mad = (dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
    mad = max(mad, mad_floor_frac * abs(med), mad_floor_abs, 1e-12)
    return {"zscore": 0.6745 * (value - med) / mad, "median": med,
            "mad": mad}


class AnomalyMonitor:
    """Watches the monitored event kinds and fires rule alerts.

    ``observe(kind, fields)`` is the single entry point (RunTelemetry
    forwards through it); it returns the list of alerts fired by that
    event, after writing each as an ``alert`` telemetry event and
    applying the configured action's side effects (stderr warn, snapshot
    request, abort request). A fired rule goes quiet for ``cooldown``
    observations of its metric — a spike fires once, not once per
    follow-up read while the history catches up.
    """

    def __init__(self, telemetry=None, *, action: str = "log",
                 window: int = 32, z_thresh: float = 6.0,
                 min_points: int = 8, cooldown: Optional[int] = None,
                 rules=RULES):
        assert action in ("log", "warn", "checkpoint", "abort"), action
        self._telemetry = telemetry
        self.action = action
        self.window = int(window)
        self.z_thresh = float(z_thresh)
        # a window smaller than min_points would otherwise gate every
        # statistical rule off forever (the deque can never hold enough
        # history) — tightening --alert_window must tighten, not disarm
        self.min_points = min(int(min_points), self.window)
        self.cooldown = int(cooldown if cooldown is not None else window)
        self.rules = tuple(rules)
        self._hist: Dict[str, deque] = {}
        self._quiet: Dict[str, int] = {}      # rule name -> obs remaining
        # group_starvation streaks: group name -> consecutive
        # observations the starvation predicate held (layer_signals.py
        # starved_groups); a clean observation breaks the streak
        self._starve: Dict[str, int] = {}
        # coverage_stall state: the last population event's distinct/
        # round and the current no-growth streak
        self._cov: Dict[str, Any] = {}
        # hh_churn state: the previous population event's top_sampled
        # id set (None until one has been seen)
        self._prev_hh: Optional[set] = None
        self.alerts: List[Dict[str, Any]] = []
        self.nonfinite_counts: Dict[str, int] = {}
        self.n_observed = 0
        self.abort_requested = False
        self._snapshot_request: Optional[Dict[str, Any]] = None

    @property
    def armed(self) -> bool:
        """The dryrun predicate: the monitor exists, has rules, and is
        attached to a stream it can write alerts into."""
        return bool(self.rules) and self._telemetry is not None

    def pop_snapshot_request(self) -> Optional[Dict[str, Any]]:
        """The first checkpoint/abort-worthy alert's context, once —
        the driver hands it to the FlightRecorder with the live state
        (the monitor never holds device arrays itself)."""
        req, self._snapshot_request = self._snapshot_request, None
        return req

    # ------------------------------------------------------------- observing

    def observe(self, kind: str, fields: Dict[str, Any]
                ) -> List[Dict[str, Any]]:
        if kind not in MONITORED_KINDS:
            return []
        self.n_observed += 1
        rnd = fields.get("round", -1)
        rnd = rnd if isinstance(rnd, int) else -1
        fired: List[Dict[str, Any]] = []
        appended: set = set()
        for rule in self.rules:
            if rule["event"] != kind:
                continue
            name = rule["name"]
            metric = f"{kind}.{rule['field']}"
            value = _extract(rule, fields)
            numeric = (isinstance(value, (int, float))
                       and not isinstance(value, bool)
                       and math.isfinite(value))
            hist = self._hist.setdefault(metric, deque(maxlen=self.window))
            quiet = self._quiet.get(name, 0)
            if quiet > 0:
                self._quiet[name] = quiet - 1
            alert = None
            if rule["kind"] == "starvation":
                # per-GROUP predicate over the layer_signals event (no
                # scalar history): a group above the mass-share floor
                # winning under the k-share floor for
                # STARVATION_WINDOW consecutive observations starves.
                # Dependency-free on purpose (layer_signals's helpers
                # import nothing at module level) — `teleview alerts`
                # replays identically on a machine without jax.
                from commefficient_tpu.telemetry.layer_signals import (
                    STARVATION_WINDOW, starved_groups)
                starved = starved_groups(fields.get("groups") or [],
                                         fields.get("grad_mass"),
                                         fields.get("topk_count"))
                now = {g for g, _, _ in starved}
                for g in [g for g in self._starve if g not in now]:
                    del self._starve[g]          # streak broken
                ripe = []
                for g, mass_share, win_share in starved:
                    streak = self._starve.get(g, 0) + 1
                    self._starve[g] = streak
                    if streak >= STARVATION_WINDOW:
                        ripe.append((g, mass_share, win_share))
                if ripe and quiet <= 0:
                    # one alert per firing, naming the hungriest group
                    # (largest starved mass share); the full list rides
                    # as an extra field for postmortems
                    g, mass_share, win_share = max(ripe,
                                                   key=lambda t: t[1])
                    alert = dict(
                        round=rnd, rule=name, severity=rule["severity"],
                        metric=f"layer_signals.starvation[{g}]",
                        value=round(win_share, 6), zscore=None,
                        median=round(mass_share, 6), mad=None,
                        window=STARVATION_WINDOW, action=self.action,
                        starved=[list(r) for r in ripe])
            elif rule["kind"] == "coverage_stall":
                # distinct-participant growth flatlining while rounds
                # advance and coverage has headroom: the sampler has
                # stopped reaching new clients. Streak state persists
                # across restarts (state_dict), like the starvation
                # streaks — a stall straddling a resume keeps counting.
                cov = fields.get("coverage")
                covered = (isinstance(cov, (int, float))
                           and not isinstance(cov, bool)
                           and float(cov) >= 0.999)
                st = self._cov
                if numeric:
                    grew = (st.get("distinct") is None
                            or float(value) > float(st["distinct"]))
                    advanced = rnd > st.get("round", -1)
                    if covered or grew or not advanced:
                        st["streak"] = 0
                    else:
                        st["streak"] = int(st.get("streak", 0)) + 1
                    st["distinct"] = float(value)
                    st["round"] = rnd
                    if (st["streak"] >= COVERAGE_STALL_WINDOW
                            and quiet <= 0):
                        alert = dict(
                            round=rnd, rule=name,
                            severity=rule["severity"],
                            metric="population.coverage_stall",
                            value=(float(cov)
                                   if isinstance(cov, (int, float))
                                   and not isinstance(cov, bool)
                                   else None),
                            zscore=None, median=None, mad=None,
                            window=COVERAGE_STALL_WINDOW,
                            action=self.action)
                        st["streak"] = 0
            elif rule["kind"] == "hh_churn":
                # Jaccard turnover between consecutive top_sampled
                # heavy-hitter sets, z-scored against its own rolling
                # history (the churn value — not the raw list — is the
                # monitored scalar; it builds history under its own
                # metric name, entered AFTER detection like every
                # other history)
                top = fields.get("top_sampled") or []
                ids = {e[0] for e in top
                       if isinstance(e, (list, tuple)) and e}
                if ids:
                    cmetric = "population.hh_turnover"
                    chist = self._hist.setdefault(
                        cmetric, deque(maxlen=self.window))
                    if self._prev_hh:
                        union = len(ids | self._prev_hh)
                        turnover = (1.0 - len(ids & self._prev_hh) / union
                                    if union else 0.0)
                        if len(chist) >= self.min_points and quiet <= 0:
                            stats = robust_z(
                                turnover, list(chist),
                                mad_floor_abs=rule.get("mad_floor_abs",
                                                       0.0))
                            if stats["zscore"] > self.z_thresh:
                                alert = dict(
                                    round=rnd, rule=name,
                                    severity=rule["severity"],
                                    metric=cmetric,
                                    value=round(turnover, 6),
                                    zscore=round(stats["zscore"], 4),
                                    median=stats["median"],
                                    mad=stats["mad"],
                                    window=len(chist),
                                    action=self.action)
                        chist.append(turnover)
                    self._prev_hh = ids
            elif rule["kind"] == "nonfinite":
                # only a metric that WAS numeric turning null is a
                # precursor; an always-null field is merely N/A
                if not numeric and value is None and len(hist) > 0:
                    self.nonfinite_counts[metric] = (
                        self.nonfinite_counts.get(metric, 0) + 1)
                    if quiet <= 0:
                        alert = dict(round=rnd, rule=name,
                                     severity=rule["severity"],
                                     metric=metric, value=None, zscore=None,
                                     median=None, mad=None,
                                     window=len(hist), action=self.action)
            elif numeric and len(hist) >= self.min_points and quiet <= 0:
                stats = robust_z(float(value), list(hist),
                                 mad_floor_abs=rule.get("mad_floor_abs",
                                                        0.0))
                z = stats["zscore"]
                breach = (z > self.z_thresh
                          if rule.get("direction") == "high"
                          else z < -self.z_thresh)
                if breach:
                    alert = dict(round=rnd, rule=name,
                                 severity=rule["severity"], metric=metric,
                                 value=float(value),
                                 zscore=round(z, 4),
                                 median=stats["median"],
                                 mad=stats["mad"],
                                 window=len(hist), action=self.action)
            # the observed value enters the history AFTER detection, so
            # the spike itself cannot vouch for its own normality —
            # and only ONCE per event, even when several rules watch
            # the same metric (loss_spike + loss_nonfinite would
            # otherwise double-append and halve the effective window)
            if numeric and metric not in appended:
                hist.append(float(value))
                appended.add(metric)
            if alert is not None:
                self._quiet[name] = self.cooldown
                fired.append(alert)
        for alert in fired:
            self._fire(alert)
        return fired

    # ----------------------------------------------------------- persistence

    def state_dict(self) -> Dict[str, Any]:
        """JSON-serializable rolling state for checkpoint meta: the
        per-metric median/MAD histories, cooldowns and nonfinite
        counters. Without this a resumed monitor restarts COLD — its
        statistical rules are disarmed for min_points observations and
        a divergence straddling the restart goes unflagged."""
        return {
            "hist": {m: list(h) for m, h in self._hist.items()},
            "quiet": dict(self._quiet),
            "nonfinite_counts": dict(self.nonfinite_counts),
            "n_observed": self.n_observed,
            # group_starvation streaks: a starvation window straddling
            # a restart must keep counting, not restart cold
            "starve": dict(self._starve),
            # population rules: the coverage_stall streak and the
            # previous heavy-hitter set (same straddle-the-restart
            # argument; pre-v11 sidecars legitimately lack both)
            "cov": dict(self._cov),
            "prev_hh": (sorted(self._prev_hh)
                        if self._prev_hh is not None else None),
        }

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self._hist = {m: deque((float(x) for x in h),
                               maxlen=self.window)
                      for m, h in (d.get("hist") or {}).items()}
        self._quiet = {r: int(q)
                       for r, q in (d.get("quiet") or {}).items()}
        self.nonfinite_counts = {m: int(n) for m, n in
                                 (d.get("nonfinite_counts") or {}).items()}
        self.n_observed = int(d.get("n_observed", 0))
        self._starve = {g: int(n)
                        for g, n in (d.get("starve") or {}).items()}
        self._cov = dict(d.get("cov") or {})
        prev = d.get("prev_hh")
        self._prev_hh = set(prev) if prev is not None else None

    # --------------------------------------------------------------- actions

    def external_alert(self, *, rnd: int, rule: str, metric: str,
                       value: Optional[float] = None,
                       severity: str = "critical") -> Dict[str, Any]:
        """Fire a non-statistical alert THROUGH the monitor (the hang
        watchdog's round_stall path): the alert event is written, the
        configured action's side effects (stderr, snapshot request,
        abort request) apply, exactly as if a rule had fired."""
        alert = dict(round=int(rnd), rule=rule, severity=severity,
                     metric=metric, value=value, zscore=None, median=None,
                     mad=None, window=0, action=self.action)
        self._fire(alert)
        return alert

    def _fire(self, alert: Dict[str, Any]) -> None:
        self.alerts.append(alert)
        if self._telemetry is not None:
            self._telemetry.event("alert", **alert)
        if self.action != "log":
            z = alert.get("zscore")
            print(f"ALERT [{alert['severity']}] {alert['rule']}: "
                  f"{alert['metric']}={alert.get('value')}"
                  + (f" (robust z {z:+.1f})" if z is not None else "")
                  + f" at round {alert['round']}", file=sys.stderr)
        if self.action in ("checkpoint", "abort"):
            if self._snapshot_request is None:
                self._snapshot_request = dict(alert)
        if self.action == "abort":
            self.abort_requested = True


class FlightRecorder:
    """One-shot postmortem bundle writer (``--alert_action checkpoint``).

    ``record(state, context)`` writes, into ``<logdir>/postmortem/``:

    - ``state.npz`` + ``state.meta.json`` — the live ``FedState``
      through the existing checkpoint layer
      (:func:`commefficient_tpu.checkpoint.save_postmortem`; a state too
      large for the single-host guard degrades to weights-only, never
      fails the run);
    - ``events.jsonl`` — the stream's last-N events (the RunTelemetry
      ring buffer), so the bundle replays without the full stream;
    - ``alert.json`` — the firing alert's context;
    - ``memory.json`` — the residency timeline (the stream's last-N
      ``memory`` snapshots, separately ring-buffered so round/span
      traffic cannot rotate them out) plus the per-executable memory
      ledgers of the watched compiled functions — an OOM postmortem
      ships WHERE the bytes went, not just the weights.

    One-shot: the FIRST alert owns the bundle (the interesting state is
    the earliest anomalous one — later alerts describe decay of a run
    the bundle already captured). Best-effort like all telemetry: a
    failed write warns and disables, never raises into the round loop.
    """

    def __init__(self, logdir: str, telemetry=None,
                 subdir: str = "postmortem"):
        self.path = os.path.join(logdir, subdir)
        self._telemetry = telemetry
        self.written: Optional[str] = None
        # whether the bundle on disk carries state.npz: an events-only
        # stall bundle must not consume the one-shot slot for state —
        # see record()
        self._state_written = False

    def record(self, state, context: Dict[str, Any]) -> Optional[str]:
        """``state=None`` writes an events-only bundle (no ``state.npz``)
        — the hang-watchdog path, where fetching device state is exactly
        the operation that may be hung. One-shot applies to the EVENTS
        side; a later state-carrying alert (NaN abort after a stall
        alert already claimed the bundle) UPGRADES the bundle with
        ``state.npz`` instead of being swallowed — the recorder exists
        for exactly that snapshot."""
        if self.written is not None:
            if state is None or self._state_written:
                return self.written
            # upgrade path: add the state snapshot to the existing
            # events-only bundle; the first firing's events/alert.json
            # (the earliest anomalous window) stay as written
            try:
                from commefficient_tpu.checkpoint import save_postmortem
                save_postmortem(os.path.join(self.path, "state"), state,
                                meta={"alert": context})
                self._state_written = True
                print(f"flight recorder: state.npz added to the "
                      f"events-only bundle at {self.path}",
                      file=sys.stderr)
            except Exception as e:  # noqa: BLE001
                print(f"WARNING: flight recorder state upgrade failed "
                      f"({e})", file=sys.stderr)
            return self.written
        try:
            os.makedirs(self.path, exist_ok=True)
            if state is not None:
                from commefficient_tpu.checkpoint import save_postmortem
                save_postmortem(os.path.join(self.path, "state"), state,
                                meta={"alert": context})
                self._state_written = True
            if self._telemetry is not None:
                with open(os.path.join(self.path, "events.jsonl"),
                          "w") as f:
                    # snapshot: the watchdog thread records bundles while
                    # the round loop keeps appending to the ring —
                    # iterating the live deque would raise mutated-
                    # during-iteration and lose the bundle
                    for ev in list(self._telemetry.recent):
                        f.write(json.dumps(ev) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                # memory.json: residency timeline + per-executable
                # ledgers (see class docstring). getattr-guarded — a
                # minimal telemetry stand-in without the v6 memory
                # machinery still gets the rest of the bundle.
                watcher = getattr(self._telemetry, "_watcher", None)
                mem = {
                    "residency": list(getattr(self._telemetry,
                                              "recent_memory", ())),
                    "ledgers": dict(getattr(watcher, "memory", {})
                                    if watcher is not None else {}),
                }
                if mem["residency"] or mem["ledgers"]:
                    with open(os.path.join(self.path, "memory.json"),
                              "w") as f:
                        json.dump(mem, f, indent=1)
                        f.flush()
                        os.fsync(f.fileno())
                # the stream itself must survive whatever comes next
                self._telemetry.fsync()
            with open(os.path.join(self.path, "alert.json"), "w") as f:
                json.dump(context, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
        except Exception as e:  # noqa: BLE001 - observability never kills
            print(f"WARNING: flight recorder failed ({e})", file=sys.stderr)
            return None
        self.written = self.path
        print(f"flight recorder: postmortem bundle at {self.path}",
              file=sys.stderr)
        return self.written
