"""Layer-wise compression attribution: per-parameter-group recovery
signals, computed INSIDE the jitted round.

Every signal in telemetry/signals.py is one scalar over the whole (d,)
vector — enough to see THAT recovery degrades at high compression
(round 5's EF blowups), not WHERE. The FetchSGD lineage (PAPER.md
§2.1/§2.3) predicts a specifically per-layer failure mode: the round's
single global top-k race is dominated by large high-mass tensors
(conv/attention kernels), small-mass parameter groups (biases, norms,
embeddings) never win coordinates, and their signal rots in the error
accumulator. This module measures exactly that: the model pytree is
partitioned into named groups mapped to ravel-order index ranges (the
same leaf order ``jax.flatten_util`` and the PR-9 ``encode_grad_tree``
leaf-range stream walk), and the round reduces its dense quantities
per group (ops/segments.py scatter-adds keyed by a precomputed int32
group-id map — on a mesh each device reduces its coordinate shard and
ONE small (G,) psum recombines; the collective ledger gates against a
per-group unroll):

- ``grad_mass``   : per-group squared-L2 of the dense aggregated
                    gradient, where one exists in the round (dense
                    modes; sketch only via the dense-preimage state or
                    the single-device deferred-encode capture). Null —
                    never fake zero — where the dense gradient does not
                    materialize (fused-encode and mesh sketch rounds:
                    restoring it would cost exactly the (d,) buffer /
                    collective those paths exist to remove).
- ``update_mass`` : per-group squared-L2 of the applied update — the
                    recovered side, which always exists.
- ``topk_count``  : top-k support count landing in the group (segment
                    count over the update's nonzero support — sums to
                    k for the sparsifying modes, to the group sizes for
                    dense modes).
- ``error_mass``  : per-group squared-L2 of the NEW error accumulator,
                    where the EF state is dense (dense-mode Verror,
                    sketch dense-preimage, or the ``--signals_exact``
                    dense shadow pair on FedState). The starvation
                    signature is this mass RISING in a group that never
                    wins coordinates.
- ``hh_overlap``  : per-group heavy-hitter recovery — of the exact
                    top-k winners of the dense pre-feedback error that
                    land in the group, the fraction the update's
                    support recovered (``--signals_exact`` only, same
                    availability as ``topk_overlap``). NaN for groups
                    that own no winner this round.

Masses are squared L2 (energy) on purpose: energies are additive, so
the conservation laws the dryrun gate asserts are exact — per-group
masses sum to the matching whole-vector signal norm squared, support
counts sum to nnz(update) (= k for sketch/top-k modes). Shares are a
host-side division (teleview layers prints them).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

LAYER_SIGNAL_KEYS = (
    "grad_mass", "update_mass", "topk_count", "error_mass", "hh_overlap",
)

SIGNAL_GROUP_MODES = ("coarse", "leaf", "off")

# group_starvation rule thresholds (telemetry/health.py + teleview
# layers share these): a group holding more than MASS_SHARE of the
# round's dense gradient energy while winning less than WIN_SHARE of
# the k top-k coordinates, for WINDOW consecutive observations, is
# starving — its gradient signal exists but never crosses the channel.
# WIN_SHARE is calibrated on the committed hard-v2 attribution arms
# (runs/BREAKDOWN_layers.md): at the 5% mass floor a group under 2% of
# k is >= 2.5x under-proportional (the measured starved head group sat
# at 10-50% mass for 1-3% of k); the flagship 2.6x arm flags head once
# late, the 10x arm flags it early and repeatedly — the dose response
# the adaptive controller keys on.
STARVATION_MASS_SHARE = 0.05
STARVATION_WIN_SHARE = 0.02
STARVATION_WINDOW = 4


def _comps(key_path) -> List[str]:
    """Path components of one tree_flatten_with_path entry, lowercased,
    with the flax 'params' wrapper stripped."""
    out = []
    for entry in key_path:
        k = getattr(entry, "key", None)
        if k is None:
            k = getattr(entry, "idx", None)
        if k is None:
            k = getattr(entry, "name", None)
        out.append(str(k).lower())
    return [c for c in out if c != "params"]


@dataclass(frozen=True)
class GroupSpec:
    """Named parameter groups over the ravel-order coordinate line.

    ``names``/``sizes`` are parallel (G,) tuples; ``ranges`` holds
    ``(start, end, group_index)`` half-open coordinate ranges in ravel
    order (a group may own several — per-block splits of scan-stacked
    transformer leaves, interleaved norm/bias leaves). Ranges tile
    [0, d) exactly: every coordinate belongs to exactly one group
    (tests pin the tiling and the boundary behavior)."""
    names: Tuple[str, ...]
    sizes: Tuple[int, ...]
    ranges: Tuple[Tuple[int, int, int], ...]
    d: int

    @property
    def n_groups(self) -> int:
        return len(self.names)

    def gid(self, d_pad: Optional[int] = None):
        """The (d_pad,) int32 group-id map the in-jit reductions key
        off. Coordinates >= d (mesh padding) map to ``n_groups`` —
        out of bounds for the (G,) buckets, so the scatter DROPS them
        (ops/segments.py): padding lands in no group."""
        import numpy as np
        d_pad = self.d if d_pad is None else int(d_pad)
        gid = np.full((d_pad,), self.n_groups, np.int32)
        for start, end, g in self.ranges:
            gid[start:end] = g
        return gid


def _coarse_name(comps: List[str], ndim: int) -> str:
    """Coarse group of one NON-stacked leaf by path pattern: embeddings
    and heads by name, everything else stage-level (the first module
    component) with 1-D leaves (biases/norms/scales) split into the
    stage's norm-bias group — the small-mass tensors the starvation
    rule exists to watch."""
    last = comps[-1] if comps else ""
    for c in comps:
        if c in ("wte", "wpe") or "embed" in c:
            return "embed"
    for c in comps:
        if "head" in c or c in ("classifier", "score", "logits"):
            return "head"
    top = comps[0] if comps else "params"
    if ndim <= 1 or last in ("bias", "scale", "b", "g"):
        return f"{top}/norm-bias"
    return top


def _block_sub(comps: List[str]) -> str:
    """Sub-group of one scan-stacked transformer-block leaf:
    attn / mlp / norm-bias (models/gpt2.py's h/block layout)."""
    last = comps[-1]
    mods = comps[comps.index("block") + 1: -1] or [last]
    mod = mods[0]
    if mod.startswith("ln") or "norm" in mod or last in ("bias", "scale"):
        return "norm-bias"
    if "mlp" in mod or "fc" in mod:
        return "mlp"
    if "attn" in mod or mod == "c_proj":
        return "attn"
    return mod


def make_group_spec(params: Any, mode: str = "coarse") -> GroupSpec:
    """Partition a parameter pytree into named coordinate groups.

    ``mode="coarse"``: path-pattern groups — embed / h<i>/attn /
    h<i>/mlp / h<i>/norm-bias / head for the GPT-2 layout (scan-stacked
    ``h/block`` leaves are split along their leading block dim into
    per-block ravel ranges — the stacked layout keeps each block's
    slice contiguous inside the leaf), stage-level (top module, with a
    norm-bias split for 1-D leaves) for conv nets. ``mode="leaf"``: one
    group per pytree leaf, named by its path. Leaves walk in ravel
    order (``jax.tree_util.tree_leaves`` order — the layout every
    ``unravel`` consumer shares, and the PR-9 encode stream's order).
    """
    import jax

    if mode not in ("coarse", "leaf"):
        raise ValueError(f"signal_groups mode {mode!r} not in "
                         f"{SIGNAL_GROUP_MODES[:-1]}")
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    names: List[str] = []
    index: Dict[str, int] = {}
    ranges: List[Tuple[int, int, int]] = []

    def gidx(name: str) -> int:
        if name not in index:
            index[name] = len(names)
            names.append(name)
        return index[name]

    off = 0
    for kp, leaf in leaves:
        comps = _comps(kp)
        n = 1
        for s in leaf.shape:
            n *= int(s)
        if mode == "leaf":
            ranges.append((off, off + n, gidx("/".join(comps) or "leaf")))
        elif "block" in comps and leaf.ndim >= 2:
            # scan-stacked transformer blocks: leading dim = block
            # index, so block b owns the contiguous ravel sub-range
            # [off + b*chunk, off + (b+1)*chunk) of this leaf
            n_blocks = int(leaf.shape[0])
            chunk = n // n_blocks
            sub = _block_sub(comps)
            for b in range(n_blocks):
                ranges.append((off + b * chunk, off + (b + 1) * chunk,
                               gidx(f"h{b}/{sub}")))
        else:
            ranges.append((off, off + n, gidx(_coarse_name(comps,
                                                           leaf.ndim))))
        off += n
    sizes = [0] * len(names)
    for start, end, g in ranges:
        sizes[g] += end - start
    return GroupSpec(names=tuple(names), sizes=tuple(sizes),
                     ranges=tuple(ranges), d=off)


def layer_group_signals(cfg, *, gid, n_groups: int, update,
                        grad_dense=None, err_dense=None, err_pre=None
                        ) -> Dict[str, Any]:
    """Compute the round's per-group signal dict (traced inside the
    round step). ``update`` is the applied weight update exactly as the
    runtime holds it pre-padding (true-d, or the mesh-padded sharded
    vector — gid maps padding out of every group, so either length is
    sound); ``grad_dense``/``err_dense`` are the dense aggregated
    gradient / NEW dense EF accumulator where the round holds one (None
    -> the field is emitted null, never fake zero); ``err_pre`` is the
    dense pre-feedback error for the ``--signals_exact`` heavy-hitter
    attribution (same reference round_signals' topk_overlap uses).
    Returns {key: (G,) f32 array or None}."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.ops.segments import group_sum_at, group_sum_cols

    # ONE batched segment reduction for every live dense source: the
    # columns stack into an (L, C) operand and scatter-add into (G, C)
    # buckets, so the whole per-group story costs one scatter and (on a
    # mesh) ONE small (G*C,) psum — adding a source must never add a
    # collective launch (the per-group-unroll regression class the
    # dryrun ledger gates). All live sources share the update's length
    # by construction (the runtime passes round quantities of one
    # topology — asserted, not assumed).
    cols = [("update_mass", update.astype(jnp.float32) ** 2),
            ("topk_count", (update != 0).astype(jnp.float32))]
    if grad_dense is not None:
        assert grad_dense.shape == update.shape, (grad_dense.shape,
                                                  update.shape)
        cols.append(("grad_mass", grad_dense.astype(jnp.float32) ** 2))
    if err_dense is not None:
        assert err_dense.shape == update.shape, (err_dense.shape,
                                                 update.shape)
        cols.append(("error_mass", err_dense.astype(jnp.float32) ** 2))
    buckets = group_sum_cols(jnp.stack([c for _, c in cols], axis=-1),
                             gid, n_groups)
    out: Dict[str, Any] = {name: buckets[:, j]
                           for j, (name, _) in enumerate(cols)}
    out.setdefault("grad_mass", None)
    out.setdefault("error_mass", None)
    if err_pre is not None:
        # exact top-k winners of the dense pre-feedback error,
        # attributed to their owning groups: win = winners per group,
        # rec = winners the update's support actually recovered
        _, idx = jax.lax.top_k(err_pre * err_pre, cfg.k)
        win = group_sum_at(jnp.ones(idx.shape, jnp.float32), idx,
                           gid, n_groups)
        rec = group_sum_at(update[idx] != 0, idx, gid, n_groups)
        out["hh_overlap"] = jnp.where(win > 0, rec / jnp.maximum(win, 1.0),
                                      jnp.nan)
    else:
        out["hh_overlap"] = None
    return out


def layer_signals_to_host(layer_signals: Optional[Dict[str, Any]]
                          ) -> Dict[str, Optional[List[float]]]:
    """Fetch a metrics['layer_signals'] dict to plain per-group float
    lists for the telemetry event (the caller has already synced the
    metrics pytree). None fields stay None (serialized null); NaN
    entries inside live fields serialize as per-entry nulls via the
    stream writer's _jsonable."""
    import numpy as np
    if not layer_signals:
        return {}
    return {k: ([float(x) for x in np.asarray(v)] if v is not None
                else None)
            for k, v in layer_signals.items()}


def starved_groups(groups: List[str], grad_mass, topk_count,
                   mass_share: float = STARVATION_MASS_SHARE,
                   win_share: float = STARVATION_WIN_SHARE
                   ) -> List[Tuple[str, float, float]]:
    """The starvation predicate over ONE emitted layer_signals event,
    dependency-free (health.py's rule and teleview both call it): the
    (name, mass_share, win_share) of every group holding more than
    ``mass_share`` of the round's dense gradient energy while winning
    less than ``win_share`` of the top-k coordinates. Empty when
    grad_mass is unavailable (null) — starvation is measured against
    gradient mass, never guessed."""
    if not grad_mass or not topk_count:
        return []
    gm = [v if isinstance(v, (int, float)) else 0.0 for v in grad_mass]
    tc = [v if isinstance(v, (int, float)) else 0.0 for v in topk_count]
    total_mass = sum(gm)
    total_k = sum(tc)
    if total_mass <= 0 or total_k <= 0:
        return []
    out = []
    for i, name in enumerate(groups):
        ms = gm[i] / total_mass
        ws = tc[i] / total_k
        if ms > mass_share and ws < win_share:
            out.append((str(name), ms, ws))
    return out
