"""Full-state checkpoint / resume.

The reference can only save final model weights (``--checkpoint``,
cv_train.py:418-421) — no optimizer/error/momentum state is ever saved, so a
crash loses the run (SURVEY.md §5 "no mid-run resume"). Here the WHOLE
``FedState`` pytree — PS weights, virtual momentum/error, per-client rows,
byte-accounting arrays, PRNG key, round counter — round-trips losslessly,
making mid-run resume exact: a resumed run continues the same trajectory.

Format: a single ``.npz`` per checkpoint (+ ``meta.json`` sidecar), atomic
rename on save, ``keep_last`` rotation. Orbax is deliberately not used: the
state is a flat dozen arrays, and a dependency-free format stays robust
across environments.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
from typing import Dict, Optional

import jax
import numpy as np

from commefficient_tpu.core.state import FedState

_FIELDS = [f.name for f in dataclasses.fields(FedState)]


def params_fingerprint(params) -> str:
    """Stable fingerprint of a parameter pytree's STRUCTURE (treedef + leaf
    shapes/dtypes). ``ps_weights`` is one flat vector whose meaning depends
    entirely on the ravel order of the param tree — e.g. flipping GPT-2's
    ``scan_layers`` reorders it — so resume must refuse a checkpoint written
    under a different layout instead of silently scrambling weights."""
    import hashlib
    leaves, treedef = jax.tree_util.tree_flatten(params)
    desc = str(treedef) + "|" + ";".join(
        f"{tuple(l.shape)}:{l.dtype}" for l in leaves)
    return hashlib.sha256(desc.encode()).hexdigest()[:16]


def save_state(path: str, state: FedState,
               meta: Optional[Dict] = None) -> str:
    """Write ``<path>.npz`` (+ ``<path>.meta.json``) atomically."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays = {}
    for name in _FIELDS:
        val = getattr(state, name)
        if val is not None:
            arrays[name] = np.asarray(val)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path + ".npz")
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    with open(path + ".meta.json", "w") as f:
        json.dump(meta or {}, f)
    return path + ".npz"


def load_state(path: str, sharding=None, d_pad: Optional[int] = None,
               num_clients: Optional[int] = None) -> FedState:
    """Rebuild a FedState; optional sharding pytree (from
    ``FedRuntime._state_sharding``) places arrays sharded on load.

    Migrations for checkpoints written by earlier versions / other
    topologies: a missing ``nan_round`` defaults to -1; when ``d_pad``
    (the restoring runtime's padded dense length) is given, 1-D dense
    server leaves are zero-padded or sliced to it; when ``num_clients``
    (the restoring runtime's mesh-padded client count) is given,
    per-client row arrays are padded (new rows start as fresh clients:
    zero velocity/error, current PS weights, never-participated) or
    truncated — so a single-device checkpoint resumes on a mesh and vice
    versa."""
    with np.load(path + ".npz") as z:
        kw = {name: (np.asarray(z[name]) if name in z.files else None)
              for name in _FIELDS}
    if kw.get("nan_round") is None:
        kw["nan_round"] = np.full((), -1, np.int32)
    if d_pad is not None:
        for name in ("ps_weights", "Vvelocity", "Verror",
                     "coord_last_update"):
            arr = kw.get(name)
            if arr is not None and arr.ndim == 1 and arr.shape[0] != d_pad:
                if arr.shape[0] < d_pad:
                    fill = -1 if name == "coord_last_update" else 0
                    arr = np.pad(arr, (0, d_pad - arr.shape[0]),
                                 constant_values=fill)
                else:
                    arr = arr[:d_pad]
                kw[name] = arr
    if num_clients is not None:
        for name in ("client_velocities", "client_errors",
                     "client_weights", "client_last_round"):
            arr = kw.get(name)
            if arr is None or arr.shape[0] == num_clients:
                continue
            if arr.shape[0] < num_clients:
                extra = num_clients - arr.shape[0]
                if name == "client_weights":
                    # fresh clients hold the current PS weights
                    # (init semantics, reference fed_aggregator.py:105-111)
                    d = arr.shape[1]
                    rows = np.broadcast_to(kw["ps_weights"][:d],
                                           (extra, d))
                    arr = np.concatenate([arr, rows])
                else:
                    pad = [(0, extra)] + [(0, 0)] * (arr.ndim - 1)
                    arr = np.pad(arr, pad)
            else:
                # only mesh-padding rows (never-sampled clients) are
                # droppable; a genuinely smaller client universe should
                # not reuse this checkpoint
                arr = arr[:num_clients]
            kw[name] = arr
    state = FedState(**{k: (jax.numpy.asarray(v) if v is not None else None)
                        for k, v in kw.items()})
    if sharding is not None:
        state = jax.device_put(state, sharding)
    return state


def load_meta(path: str) -> Dict:
    fn = path + ".meta.json"
    if not os.path.exists(fn):
        return {}
    with open(fn) as f:
        return json.load(f)


class CheckpointManager:
    """Rotating checkpoints under ``directory``: ``ckpt_<epoch>``,
    keeping the newest ``keep_last``."""

    def __init__(self, directory: str, keep_last: int = 3):
        self.directory = directory
        self.keep_last = keep_last
        # merged into every save's meta (drivers put the params fingerprint
        # here so resume can detect layout changes)
        self.default_meta: Dict = {}

    def _path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"ckpt_{epoch:06d}")

    def save(self, state: FedState, epoch: int,
             meta: Optional[Dict] = None) -> str:
        meta = dict(self.default_meta, **(meta or {}), epoch=epoch)
        out = save_state(self._path(epoch), state, meta)
        self._rotate()
        return out

    def _rotate(self) -> None:
        for e in self.epochs()[: -self.keep_last]:
            for suffix in (".npz", ".meta.json"):
                fn = self._path(e) + suffix
                if os.path.exists(fn):
                    os.unlink(fn)

    def epochs(self):
        if not os.path.isdir(self.directory):
            return []
        out = []
        for fn in os.listdir(self.directory):
            if fn.startswith("ckpt_") and fn.endswith(".npz"):
                out.append(int(fn[len("ckpt_"):-len(".npz")]))
        return sorted(out)

    def latest(self) -> Optional[int]:
        es = self.epochs()
        return es[-1] if es else None

    def restore_latest(self, sharding=None, expect_fingerprint=None,
                       allow_missing_fingerprint=False, d_pad=None,
                       num_clients=None):
        """Returns (state, meta) or (None, {}). When the caller carries a
        params fingerprint, a mismatch — or a checkpoint that predates
        fingerprinting and so carries none — raises instead of resuming into
        a possibly scrambled flat-weight layout (a pre-fingerprint GPT-2
        checkpoint resumed after e.g. ``scan_layers`` flipped would reorder
        the whole ravel silently). ``allow_missing_fingerprint=True`` opts
        back in to loading un-fingerprinted checkpoints."""
        e = self.latest()
        if e is None:
            return None, {}
        meta = load_meta(self._path(e))
        saved_fp = meta.get("params_fingerprint")
        if expect_fingerprint is not None:
            if saved_fp is None and not allow_missing_fingerprint:
                raise ValueError(
                    f"checkpoint {self._path(e)} carries no params "
                    "fingerprint (written by an older version), so its flat "
                    "ps_weights layout cannot be verified against the "
                    "current model. Pass allow_missing_fingerprint=True "
                    "(drivers: --resume_unverified) only if the model "
                    "configuration is unchanged since it was written.")
            if saved_fp is not None and saved_fp != expect_fingerprint:
                raise ValueError(
                    f"checkpoint {self._path(e)} was written under a "
                    f"different parameter layout (fingerprint {saved_fp} != "
                    f"{expect_fingerprint}); the flat ps_weights vector "
                    "would unravel into the wrong weights. Re-create the "
                    "run or load with the original model configuration.")
        return load_state(self._path(e), sharding=sharding, d_pad=d_pad,
                          num_clients=num_clients), meta
