"""Full-state checkpoint / resume.

The reference can only save final model weights (``--checkpoint``,
cv_train.py:418-421) — no optimizer/error/momentum state is ever saved, so a
crash loses the run (SURVEY.md §5 "no mid-run resume"). Here the WHOLE
``FedState`` pytree — PS weights, virtual momentum/error, per-client rows,
byte-accounting arrays, PRNG key, round counter — round-trips losslessly,
making mid-run resume exact: a resumed run continues the same trajectory.

Format: a single ``.npz`` per checkpoint (+ ``meta.json`` sidecar), atomic
rename on save, ``keep_last`` rotation. Orbax is deliberately not used: the
state is a flat dozen arrays, and a dependency-free format stays robust
across environments.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import sys
import tempfile
import zipfile
from typing import Dict, List, Optional

import jax
import numpy as np

from commefficient_tpu.core.state import FedState
from commefficient_tpu.faults import maybe_fault

_FIELDS = [f.name for f in dataclasses.fields(FedState)]


class CheckpointIntegrityError(ValueError):
    """A checkpoint FILE is unreadable or fails its content digests —
    truncation, a bit flip, a kill mid-write. Distinct from the semantic
    refusals (fingerprint/sketch-generation mismatch, live-state
    truncation), which mean the CONFIG is wrong and no amount of
    falling back through the rotation can fix it:
    ``CheckpointManager.restore_latest`` catches exactly this class (and
    only this class) to fall back generation-by-generation."""


def _entry_digest(arr: np.ndarray) -> str:
    """sha256 over (dtype, shape, raw bytes) of one stored array — the
    per-entry integrity record ``meta.json`` carries under "digests"."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(tuple(arr.shape)).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _open_npz(path: str):
    """np.load with every low-level failure (truncated zip, junk bytes,
    bad magic) mapped to :class:`CheckpointIntegrityError` — the
    fallback loop must be able to tell "this file is damaged" from
    "this resume is misconfigured"."""
    try:
        return np.load(path + ".npz")
    except Exception as e:
        raise CheckpointIntegrityError(
            f"checkpoint file {path}.npz is unreadable ({e})") from e


def _read_entry(z, key: str, path: str,
                digests: Optional[Dict[str, str]] = None) -> np.ndarray:
    """Read one npz entry, mapping member-level corruption (bad CRC,
    truncated stream) to CheckpointIntegrityError and verifying the
    entry's sha256 when the meta sidecar recorded one."""
    try:
        arr = z[key]
    except Exception as e:
        raise CheckpointIntegrityError(
            f"checkpoint file {path}.npz entry {key!r} is corrupt "
            f"({e})") from e
    if digests and key in digests:
        got = _entry_digest(np.asarray(arr))
        if got != digests[key]:
            raise CheckpointIntegrityError(
                f"checkpoint file {path}.npz entry {key!r} fails its "
                f"sha256 digest (stored {digests[key][:12]}..., read "
                f"{got[:12]}...): the data was corrupted after it was "
                "written")
    return arr


def params_fingerprint(params) -> str:
    """Stable fingerprint of a parameter pytree's STRUCTURE (treedef + leaf
    shapes/dtypes). ``ps_weights`` is one flat vector whose meaning depends
    entirely on the ravel order of the param tree — e.g. flipping GPT-2's
    ``scan_layers`` reorders it — so resume must refuse a checkpoint written
    under a different layout instead of silently scrambling weights."""
    import hashlib
    leaves, treedef = jax.tree_util.tree_flatten(params)
    desc = str(treedef) + "|" + ";".join(
        f"{tuple(l.shape)}:{l.dtype}" for l in leaves)
    return hashlib.sha256(desc.encode()).hexdigest()[:16]


# above this many bytes of host materialization, a plain save refuses and
# points at sharded=True (a (num_clients, d) client-state array at PERSONA
# scale — 17,568 x 124M rows — can never pass through one np.asarray)
DEFAULT_MAX_HOST_BYTES = int(os.environ.get(
    "COMMEFFICIENT_CKPT_MAX_HOST_BYTES", 8 << 30))

# "no sketch-generation check requested" sentinel for restore_latest
# (None is a meaningful value there: a non-sketch restoring run)
_UNSET = object()

# the file-damage classes restore_latest's generation fallback catches
# (see CheckpointIntegrityError): our own integrity class plus the raw
# zip/IO errors a member read can leak past the wrappers
_DAMAGE_ERRORS = (CheckpointIntegrityError, zipfile.BadZipFile,
                  OSError, EOFError, KeyError)


def _state_nbytes(state: FedState) -> int:
    return sum(getattr(state, name).nbytes for name in _FIELDS
               if getattr(state, name) is not None)


def _atomic_savez(path: str, arrays: Dict) -> Dict[str, str]:
    """Write atomically; returns the per-entry sha256 digests the meta
    sidecar records for load-time integrity verification."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    os.close(fd)
    digests = {k: _entry_digest(np.asarray(v)) for k, v in arrays.items()}
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        # crash-matrix kill-point: tmp fully written, rename pending —
        # a death here must leave the PREVIOUS generation intact and
        # only .tmp litter behind (cleaned by CheckpointManager)
        maybe_fault("mid_checkpoint_write")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return digests


def _atomic_savez_stream(path: str, entries) -> Dict[str, str]:
    """Write an npz-compatible zip one array at a time. ``entries`` yields
    (key, thunk-returning-ndarray); each thunk's result is written to the
    archive and dropped before the next is produced, so peak host memory
    is ONE entry — the point of the sharded save (np.savez would require
    every shard of every field live in a dict simultaneously, i.e. the
    full state the guard just refused to materialize). Returns per-entry
    sha256 digests, like :func:`_atomic_savez`."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    os.close(fd)
    digests: Dict[str, str] = {}
    try:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED,
                             allowZip64=True) as zf:
            for key, thunk in entries:
                arr = np.asarray(thunk())
                digests[key] = _entry_digest(arr)
                with zf.open(key + ".npy", "w", force_zip64=True) as f:
                    np.lib.format.write_array(f, arr, allow_pickle=False)
                del arr
        maybe_fault("mid_checkpoint_write")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return digests


def save_state(path: str, state: FedState, meta: Optional[Dict] = None,
               sharded: bool = False,
               max_host_bytes: int = DEFAULT_MAX_HOST_BYTES) -> str:
    """Write ``<path>.npz`` (+ ``<path>.meta.json``) atomically.

    A plain save materializes every field on the host at once
    (``np.asarray``); states whose total size exceeds ``max_host_bytes``
    are REFUSED with a clear message instead of silently OOMing the host.
    The escape hatch is ``sharded=True``: each device shard of each array
    is pulled to host and written individually (peak host memory = one
    shard), stored as ``name__shard{i}`` entries with offset metadata.
    ``load_state`` restores a same-topology sharded checkpoint by
    streaming each shard straight to its device (host peak = one shard);
    cross-topology migrations fall back to host-side reassembly, which
    does need host RAM for the full state."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if sharded:
        # plan first (shard metadata only — shapes/offsets are free), so
        # the coverage check runs before any data is pulled to host; then
        # stream shard-by-shard
        entries = [("__sharded__", lambda: np.asarray(1))]
        for name in _FIELDS:
            val = getattr(state, name)
            if val is None:
                continue
            entries.append((f"{name}__shape",
                            lambda v=val: np.asarray(v.shape, np.int64)))
            entries.append((f"{name}__dtype",
                            lambda v=val: np.asarray(str(v.dtype))))
            shards = getattr(val, "addressable_shards", None)
            if not shards:
                entries.append((f"{name}__shard0",
                                lambda v=val: np.asarray(v)))
                entries.append((f"{name}__off0",
                                lambda v=val: np.zeros(max(v.ndim, 1),
                                                       np.int64)))
                continue
            seen = set()
            i = 0
            covered = 0
            for s in shards:
                off = tuple(sl.start or 0 for sl in s.index) or (0,)
                if off in seen:   # replicated: one copy is enough
                    continue
                seen.add(off)
                entries.append((f"{name}__shard{i}",
                                lambda s=s: np.asarray(s.data)))
                entries.append((f"{name}__off{i}",
                                lambda off=off: np.asarray(off, np.int64)))
                covered += int(np.prod(s.data.shape))
                i += 1
            if covered != int(np.prod(val.shape)):
                # multi-process mesh: this host only addresses part of the
                # array — a single-host npz would silently hold garbage
                # for the rest (the load side also verifies coverage)
                raise ValueError(
                    f"sharded save of '{name}' covers only {covered} of "
                    f"{int(np.prod(val.shape))} elements from this "
                    "process (multi-host sharding). Per-host sharded "
                    "checkpointing is not supported — gather to one "
                    "process first or use a distributed checkpointer.")
        digests = _atomic_savez_stream(path + ".npz", entries)
    else:
        total = _state_nbytes(state)
        if total > max_host_bytes:
            raise ValueError(
                f"checkpoint state is {total / 2**30:.1f} GiB, above the "
                f"{max_host_bytes / 2**30:.1f} GiB single-host "
                "materialization guard — a plain np.savez would OOM the "
                "host at this scale. Pass sharded=True (per-shard "
                "streaming writes, peak host memory = one shard), or "
                "raise COMMEFFICIENT_CKPT_MAX_HOST_BYTES explicitly.")
        arrays = {}
        for name in _FIELDS:
            val = getattr(state, name)
            if val is not None:
                arrays[name] = np.asarray(val)
        digests = _atomic_savez(path + ".npz", arrays)
    # per-entry sha256 digests ride the sidecar: load_state verifies
    # them so a bit-flipped (CRC-evading) or partially-rewritten archive
    # is caught as CheckpointIntegrityError instead of decoding garbage
    meta = dict(meta or {}, digests=digests)
    with open(path + ".meta.json", "w") as f:
        json.dump(meta, f)
    return path + ".npz"


def save_postmortem(path: str, state: FedState,
                    meta: Optional[Dict] = None) -> str:
    """Flight-recorder snapshot (telemetry/health.py): ``save_state``
    with degradation instead of refusal. A postmortem happens exactly
    when the run is in trouble, so a state too large for the single-host
    materialization guard must not abort the recorder — it falls back to
    the ``ps_weights`` vector alone (the piece a replay needs first) and
    says so in the meta sidecar. Uses the normal checkpoint format, so
    ``load_state`` reads a full bundle back unchanged."""
    meta = dict(meta or {})
    try:
        return save_state(path, state, meta)
    except ValueError as e:
        meta["degraded"] = f"weights-only postmortem: {e}"
        print(f"WARNING: postmortem degraded to weights-only ({e})",
              file=sys.stderr)
        digests = _atomic_savez_stream(
            path + ".npz",
            [("ps_weights__shape",
              lambda: np.asarray(state.ps_weights.shape, np.int64)),
             ("ps_weights__dtype",
              lambda: np.asarray(str(state.ps_weights.dtype))),
             ("ps_weights__shard0",
              lambda: np.asarray(state.ps_weights)),
             ("ps_weights__off0",
              lambda: np.zeros(1, np.int64)),
             ("__sharded__", lambda: np.asarray(1))])
        with open(path + ".meta.json", "w") as f:
            json.dump(dict(meta, digests=digests), f)
        return path + ".npz"


def _shapes_need_migration(z, d_pad, num_clients, d_row_pad) -> bool:
    """Whether any stored field's shape differs from the restoring
    runtime's targets (in which case the host-side migration path must
    run)."""
    for name in ("ps_weights", "Vvelocity", "Verror", "coord_last_update",
                 "async_buffer"):
        if d_pad is not None and f"{name}__shape" in z.files:
            shape = tuple(z[f"{name}__shape"])
            if len(shape) == 1 and shape[0] != d_pad:
                return True
    for name in ("client_velocities", "client_errors", "client_weights",
                 "client_last_round"):
        if f"{name}__shape" not in z.files:
            continue
        shape = tuple(z[f"{name}__shape"])
        if num_clients is not None and shape[0] != num_clients:
            return True
        if (d_row_pad is not None and len(shape) == 2
                and name in ("client_velocities", "client_errors")
                and shape[1] != d_row_pad):
            return True
    return False


class _LayoutMismatch(Exception):
    pass


def _try_streaming_restore(z, sharding, path: str = "",
                           digests: Optional[Dict[str, str]] = None
                           ) -> Optional[FedState]:
    """Same-topology restore of a sharded checkpoint WITHOUT ever
    materializing a full field on the host: each device shard is read
    from the archive and placed directly (host peak = one shard). Only
    possible when every requested device region exactly matches a stored
    shard; returns None otherwise (caller falls back to the host path —
    which needs host RAM for the full state, the price of cross-topology
    migration)."""
    fields: Dict[str, Optional[jax.Array]] = {}
    for name in _FIELDS:
        if f"{name}__shape" not in z.files:
            fields[name] = None
            continue
        sh = getattr(sharding, name, None)
        if sh is None:
            return None
        shape = tuple(int(x) for x in z[f"{name}__shape"])
        offmap = {}
        i = 0
        while f"{name}__off{i}" in z.files:
            offmap[tuple(int(o) for o in z[f"{name}__off{i}"])] = i
            i += 1

        def cb(index, name=name, offmap=offmap, shape=shape):
            starts = tuple(sl.start or 0 for sl in index) or (0,)
            want = tuple((sl.stop if sl.stop is not None else dim)
                         - (sl.start or 0)
                         for sl, dim in zip(index, shape))
            i = offmap.get(starts if shape else (0,))
            if i is None:
                raise _LayoutMismatch(name)
            arr = _read_entry(z, f"{name}__shard{i}", path, digests)
            if tuple(arr.shape) != want:
                raise _LayoutMismatch(name)
            return arr

        try:
            fields[name] = jax.make_array_from_callback(shape, sh, cb)
        except _LayoutMismatch:
            return None
    return FedState(**fields)


def _load_arrays(path: str, digests: Optional[Dict[str, str]] = None
                 ) -> Dict[str, Optional[np.ndarray]]:
    """Read either npz layout back into full per-field host arrays,
    verifying per-entry digests when the meta sidecar recorded them."""
    with _open_npz(path) as z:
        if "__sharded__" not in z.files:
            return {name: (np.asarray(_read_entry(z, name, path, digests))
                           if name in z.files else None)
                    for name in _FIELDS}
        kw: Dict[str, Optional[np.ndarray]] = {}
        for name in _FIELDS:
            if f"{name}__shape" not in z.files:
                kw[name] = None
                continue
            shape = tuple(_read_entry(z, f"{name}__shape", path, digests))
            out = np.empty(shape, dtype=str(
                _read_entry(z, f"{name}__dtype", path, digests)))
            i = 0
            covered = 0
            while f"{name}__shard{i}" in z.files:
                shard = _read_entry(z, f"{name}__shard{i}", path, digests)
                off = tuple(_read_entry(z, f"{name}__off{i}", path,
                                        digests))
                idx = tuple(slice(o, o + s)
                            for o, s in zip(off, shard.shape))
                out[idx if shape else ...] = shard
                covered += int(np.prod(shard.shape))
                i += 1
            if covered != int(np.prod(shape)):
                raise ValueError(
                    f"sharded checkpoint entry '{name}' covers only "
                    f"{covered} of {int(np.prod(shape))} elements — the "
                    "file was written by a process that could not address "
                    "the whole array; np.empty would silently supply "
                    "garbage for the rest.")
            kw[name] = out
        return kw


def load_state(path: str, sharding=None, d_pad: Optional[int] = None,
               num_clients: Optional[int] = None,
               d_row_pad: Optional[int] = None,
               verify_digests: Optional[Dict[str, str]] = None
               ) -> FedState:
    """Rebuild a FedState; optional sharding pytree (from
    ``FedRuntime._state_sharding``) places arrays sharded on load.

    Migrations for checkpoints written by earlier versions / other
    topologies: a missing ``nan_round`` defaults to -1; when ``d_pad``
    (the restoring runtime's padded dense length) is given, 1-D dense
    server leaves are zero-padded or sliced to it; when ``d_row_pad``
    (the restoring runtime's per-client dense row length — mesh-padded
    for the column-sharded home layout) is given, 2-D velocity/error
    rows are zero-padded or sliced along dim 1; when ``num_clients``
    (the restoring runtime's mesh-padded client count) is given,
    per-client row arrays are padded (new rows start as fresh clients:
    zero velocity/error, current PS weights, never-participated) or
    truncated — so a single-device checkpoint resumes on a mesh and vice
    versa. Truncation is only legal for PADDING: sliced-off velocity/
    error rows (a smaller client universe) and sliced-off row columns
    must be all-zero, else the load raises instead of silently dropping
    live client state.

    Sharded checkpoints restoring to the SAME topology (shapes match,
    sharding given) stream each shard straight to its device — host peak
    = one shard, so states bigger than host RAM round-trip. Any shape
    migration falls back to host-side reassembly."""
    if sharding is not None:
        with _open_npz(path) as z:
            if ("__sharded__" in z.files
                    and not _shapes_need_migration(z, d_pad, num_clients,
                                                   d_row_pad)):
                state = _try_streaming_restore(z, sharding, path,
                                               verify_digests)
                if state is not None:
                    # apply the same missing-field migration defaults as
                    # the host path below — the two restore paths must not
                    # drift (a file missing nan_round must come back as
                    # -1, not None, either way)
                    if state.nan_round is None:
                        state = dataclasses.replace(
                            state, nan_round=jax.numpy.full((), -1,
                                                            jax.numpy.int32))
                    return state
    kw = _load_arrays(path, digests=verify_digests)
    if kw.get("nan_round") is None:
        kw["nan_round"] = np.full((), -1, np.int32)
    if d_pad is not None:
        # async_buffer migrates like the other dense server vectors; a
        # non-empty buffer is loudly reset by the driver anyway
        # (core/async_agg.reconcile_resumed_state), so padding/slicing
        # zeros here only keeps the shapes loadable across topologies
        for name in ("ps_weights", "Vvelocity", "Verror",
                     "coord_last_update", "async_buffer"):
            arr = kw.get(name)
            if arr is not None and arr.ndim == 1 and arr.shape[0] != d_pad:
                if arr.shape[0] < d_pad:
                    fill = -1 if name == "coord_last_update" else 0
                    arr = np.pad(arr, (0, d_pad - arr.shape[0]),
                                 constant_values=fill)
                else:
                    arr = arr[:d_pad]
                kw[name] = arr
    if d_row_pad is not None:
        # dense client rows: true d single-device, d_row_pad on a mesh
        for name in ("client_velocities", "client_errors"):
            arr = kw.get(name)
            if arr is None or arr.ndim != 2 or arr.shape[1] == d_row_pad:
                continue
            if arr.shape[1] < d_row_pad:
                arr = np.pad(arr, ((0, 0), (0, d_row_pad - arr.shape[1])))
            else:
                dropped = arr[:, d_row_pad:]
                if np.any(dropped):
                    raise ValueError(
                        f"cannot narrow {name} rows from {arr.shape[1]} to "
                        f"{d_row_pad}: the sliced-off columns carry "
                        "non-zero state (a different model, not mesh "
                        "padding). Restore with the original model "
                        "configuration.")
                arr = arr[:, :d_row_pad]
            kw[name] = arr
    if num_clients is not None:
        for name in ("client_velocities", "client_errors",
                     "client_weights", "client_last_round"):
            arr = kw.get(name)
            if arr is None or arr.shape[0] == num_clients:
                continue
            if arr.shape[0] < num_clients:
                extra = num_clients - arr.shape[0]
                if name == "client_weights":
                    # fresh clients hold the current PS weights
                    # (init semantics, reference fed_aggregator.py:105-111)
                    d = arr.shape[1]
                    rows = np.broadcast_to(kw["ps_weights"][:d],
                                           (extra, d))
                    arr = np.concatenate([arr, rows])
                else:
                    pad = [(0, extra)] + [(0, 0)] * (arr.ndim - 1)
                    arr = np.pad(arr, pad)
            else:
                # only mesh-padding rows (never-sampled clients) are
                # droppable: dropped velocity/error must be zero — a
                # genuinely smaller client universe loses live state and
                # must not silently reuse this checkpoint
                dropped = arr[num_clients:]
                if name in ("client_velocities", "client_errors") and \
                        np.any(dropped):
                    raise ValueError(
                        f"cannot truncate {name} from {arr.shape[0]} to "
                        f"{num_clients} clients: the dropped rows carry "
                        "non-zero velocity/error state (live clients, not "
                        "mesh padding). Restore with num_clients >= "
                        f"{arr.shape[0]}, or migrate explicitly.")
                if name == "client_weights" or (
                        name == "client_last_round" and np.any(dropped)):
                    print(f"checkpoint: dropping {len(dropped)} "
                          f"{name} rows (cannot verify freshness)",
                          file=sys.stderr)
                arr = arr[:num_clients]
            kw[name] = arr
    state = FedState(**{k: (jax.numpy.asarray(v) if v is not None else None)
                        for k, v in kw.items()})
    if sharding is not None:
        state = jax.device_put(state, sharding)
    return state


def load_meta(path: str) -> Dict:
    fn = path + ".meta.json"
    if not os.path.exists(fn):
        return {}
    with open(fn) as f:
        return json.load(f)


class CheckpointManager:
    """Rotating checkpoints under ``directory``: ``ckpt_<epoch>`` at the
    epoch cadence, plus out-of-cadence tagged generations
    (``ckpt_<epoch>_r<round>_preempt`` — the graceful-preemption path
    writes these mid-epoch). All generations share one rotation ordered
    by ``(epoch, round_in_epoch)``, keeping the newest ``keep_last``."""

    def __init__(self, directory: str, keep_last: int = 3,
                 sharded: bool = False,
                 max_host_bytes: int = DEFAULT_MAX_HOST_BYTES):
        self.directory = directory
        self.keep_last = keep_last
        # save_state passthrough (drivers: --checkpoint_sharded): without
        # this, a run whose state exceeds the host-materialization guard
        # could never reach the advertised sharded=True escape hatch
        self.sharded = sharded
        self.max_host_bytes = max_host_bytes
        # merged into every save's meta (drivers put the params fingerprint
        # here so resume can detect layout changes)
        self.default_meta: Dict = {}
        # integrity fallbacks the LAST restore_latest performed, for the
        # driver's `fault` telemetry events: [{"path", "error"}, ...]
        self.restore_fallbacks: List[Dict[str, str]] = []

    def _path(self, epoch: int, round_in_epoch: int = 0,
              tag: Optional[str] = None) -> str:
        stem = f"ckpt_{epoch:06d}"
        if round_in_epoch or tag:
            stem += f"_r{round_in_epoch:06d}_{tag or 'preempt'}"
        return os.path.join(self.directory, stem)

    def clean_stale_tmp(self) -> List[str]:
        """Remove ``*.tmp`` litter a kill mid-write left behind (the
        atomic writers unlink their tmp on every LIVE exit path, but
        ``os._exit``/SIGKILL skips ``finally``). Called before every
        save so the directory self-heals on the first post-crash
        checkpoint; returns the removed paths."""
        removed = []
        if os.path.isdir(self.directory):
            for fn in os.listdir(self.directory):
                if fn.endswith(".tmp"):
                    full = os.path.join(self.directory, fn)
                    try:
                        os.unlink(full)
                        removed.append(full)
                    except OSError:
                        pass
        if removed:
            print(f"checkpoint: removed {len(removed)} stale .tmp "
                  "file(s) from an interrupted write", file=sys.stderr)
        return removed

    def save(self, state: FedState, epoch: int,
             meta: Optional[Dict] = None, round_in_epoch: int = 0,
             tag: Optional[str] = None) -> str:
        meta = dict(self.default_meta, **(meta or {}), epoch=epoch,
                    round_in_epoch=int(round_in_epoch))
        if tag:
            meta["tag"] = tag
        self.clean_stale_tmp()
        out = save_state(self._path(epoch, round_in_epoch, tag), state,
                         meta, sharded=self.sharded,
                         max_host_bytes=self.max_host_bytes)
        self._rotate()
        return out

    def _rotate(self) -> None:
        for _, _, stem in self.generations()[: -self.keep_last]:
            for suffix in (".npz", ".meta.json"):
                fn = os.path.join(self.directory, stem) + suffix
                if os.path.exists(fn):
                    os.unlink(fn)

    @staticmethod
    def _parse_stem(stem: str):
        """``ckpt_EEEEEE[_rRRRRRR_tag]`` -> (epoch, round) or None."""
        body = stem[len("ckpt_"):]
        parts = body.split("_")
        try:
            epoch = int(parts[0])
        except ValueError:
            return None
        rnd = 0
        if len(parts) >= 2 and parts[1].startswith("r"):
            try:
                rnd = int(parts[1][1:])
            except ValueError:
                return None
        return epoch, rnd

    def generations(self):
        """Every checkpoint generation as ``(epoch, round_in_epoch,
        stem)``, sorted oldest -> newest. Epoch-cadence checkpoints sit
        at round 0; a preempt checkpoint written r rounds into epoch e
        sorts between the epoch-e and epoch-e+1 generations."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for fn in os.listdir(self.directory):
            if not (fn.startswith("ckpt_") and fn.endswith(".npz")):
                continue
            stem = fn[: -len(".npz")]
            key = self._parse_stem(stem)
            if key is not None:
                out.append((key[0], key[1], stem))
        return sorted(out)

    def epochs(self):
        """Epoch-cadence generations only (back-compat surface; the
        rotation and restore walk :meth:`generations`)."""
        return sorted(e for e, r, _ in self.generations() if r == 0)

    def latest(self) -> Optional[int]:
        es = self.epochs()
        return es[-1] if es else None

    def restore_latest(self, sharding=None, expect_fingerprint=None,
                       allow_missing_fingerprint=False, d_pad=None,
                       num_clients=None, d_row_pad=None,
                       expect_sketch_gen=_UNSET,
                       sketch_mismatch_ok=False,
                       expect_async_gen=_UNSET,
                       async_mismatch_ok=False):
        """Returns (state, meta) or (None, {}). When the caller carries a
        params fingerprint, a mismatch — or a checkpoint that predates
        fingerprinting and so carries none — raises instead of resuming into
        a possibly scrambled flat-weight layout (a pre-fingerprint GPT-2
        checkpoint resumed after e.g. ``scan_layers`` flipped would reorder
        the whole ravel silently). ``allow_missing_fingerprint=True`` opts
        back in to loading un-fingerprinted checkpoints.

        ``expect_sketch_gen`` (the restoring run's sketch-generation
        marker, see cv_train.setup_checkpointing; pass None for non-sketch
        runs) is checked against the checkpoint's meta BEFORE any state is
        materialized: a marker mismatch raises the explanatory error here
        — in particular, a table-state checkpoint resumed under
        ``sketch_server_state='dense'`` (or vice versa) must fail with the
        layout explanation, not with the raw array-shape error the load
        itself would hit. ``sketch_mismatch_ok=True`` (drivers:
        --resume_unverified) downgrades SAME-layout marker mismatches to
        the caller's discard-and-continue path; cross-layout mismatches
        still raise (there is no state to discard INTO — the saved tables
        and the runtime's pre-images do not even have the same shape).

        Integrity fallback: a generation whose FILE is damaged — a
        truncated zip, a bit flip caught by the per-entry sha256
        digests, an unreadable meta sidecar — is skipped with a loud
        warning and the restore falls back to the PREVIOUS generation
        in the rotation (``restore_fallbacks`` records each skip for
        the driver's `fault` telemetry). Semantic refusals above still
        raise: a wrong config cannot be fixed by an older file. Only
        when EVERY generation is damaged does the restore raise the
        last integrity error — silently restarting a --resume run from
        scratch would be worse than stopping."""
        self.restore_fallbacks = []
        gens = self.generations()
        if not gens:
            return None, {}
        last_err: Optional[Exception] = None
        for _, _, stem in reversed(gens):
            path = os.path.join(self.directory, stem)
            try:
                meta = self._load_meta_checked(path)
            except CheckpointIntegrityError as err:
                self._record_fallback(path, err)
                last_err = err
                continue
            # semantic guards: checked against the META before any state
            # is materialized, and NEVER downgraded to a fallback — a
            # config mismatch is the same in every generation
            if expect_sketch_gen is not _UNSET \
                    and expect_sketch_gen is not None:
                self._check_sketch_gen(meta.get("sketch_gen"),
                                       expect_sketch_gen,
                                       sketch_mismatch_ok, path)
            if expect_async_gen is not _UNSET \
                    and expect_async_gen is not None:
                # async-aggregation vintage, checked against the META
                # before any state is materialized (the sketch_gen
                # pattern): an async run resuming a checkpoint that
                # carries no async ledger cannot verify the
                # buffer/commit bookkeeping it is about to continue
                self._check_async_gen(meta.get("async_gen"),
                                      expect_async_gen,
                                      async_mismatch_ok, path)
            saved_fp = meta.get("params_fingerprint")
            if expect_fingerprint is not None:
                if saved_fp is None and not allow_missing_fingerprint:
                    raise ValueError(
                        f"checkpoint {path} carries no params "
                        "fingerprint (written by an older version), so "
                        "its flat ps_weights layout cannot be verified "
                        "against the current model. Pass "
                        "allow_missing_fingerprint=True (drivers: "
                        "--resume_unverified) only if the model "
                        "configuration is unchanged since it was "
                        "written.")
                if saved_fp is not None and saved_fp != expect_fingerprint:
                    raise ValueError(
                        f"checkpoint {path} was written under a "
                        f"different parameter layout (fingerprint "
                        f"{saved_fp} != {expect_fingerprint}); the flat "
                        "ps_weights vector would unravel into the wrong "
                        "weights. Re-create the run or load with the "
                        "original model configuration.")
            try:
                state = load_state(path, sharding=sharding, d_pad=d_pad,
                                   num_clients=num_clients,
                                   d_row_pad=d_row_pad,
                                   verify_digests=meta.get("digests"))
            except _DAMAGE_ERRORS as err:
                # CheckpointIntegrityError plus the raw zip/IO classes a
                # member-level read can still leak (e.g. a corrupt
                # __shape entry inspected by the migration probe) —
                # never the semantic ValueErrors, which propagate above
                self._record_fallback(path, err)
                last_err = err
                continue
            return state, meta
        assert last_err is not None
        raise CheckpointIntegrityError(
            f"every checkpoint generation under {self.directory} is "
            f"damaged ({len(self.restore_fallbacks)} tried); refusing "
            "to silently restart from scratch. Last error: "
            f"{last_err}")

    @staticmethod
    def _load_meta_checked(path: str) -> Dict:
        """load_meta with sidecar corruption mapped to the integrity
        class, so a meta.json truncated by the same crash that damaged
        the npz also falls back instead of crashing the resume."""
        try:
            return load_meta(path)
        except (OSError, ValueError) as e:
            raise CheckpointIntegrityError(
                f"checkpoint file {path}.meta.json is unreadable "
                f"({e})") from e

    def _record_fallback(self, path: str, err: Exception) -> None:
        self.restore_fallbacks.append({"path": path, "error": str(err)})
        print(f"WARNING: checkpoint {path} is unreadable or corrupt "
              f"({err}); falling back to the previous generation in "
              "the rotation", file=sys.stderr)

    @staticmethod
    def _check_sketch_gen(saved_gen, expect_gen: str, mismatch_ok: bool,
                          path: str) -> None:
        """Sketch state (momentum/error tables or dense pre-images) only
        decodes under the EXACT construction that encoded it; see the
        marker format in cv_train.setup_checkpointing."""
        if saved_gen == expect_gen:
            return
        # server-state LAYOUT first: "-densestate" markers store (d,)
        # pre-image buffers, table markers store (r, c) tables (and
        # pre-marker checkpoints predate the dense path entirely) — no
        # discard can cross layouts, so --resume_unverified cannot help
        dense_saved = (isinstance(saved_gen, str)
                       and saved_gen.endswith("-densestate"))
        dense_want = expect_gen.endswith("-densestate")
        if dense_saved != dense_want:
            saved_layout = "dense (d,) pre-images" if dense_saved \
                else "(r, c) tables"
            want_layout = "dense (d,) pre-images" if dense_want \
                else "(r, c) tables"
            raise ValueError(
                f"checkpoint {path} stores its sketch server state as "
                f"{saved_layout} (generation {saved_gen!r}) but this run "
                f"uses {want_layout} (generation {expect_gen!r}): the "
                "saved momentum/error state does not even have this "
                "run's shapes, so it cannot be loaded OR discarded in "
                "place. Re-create the run, or restore under the "
                "original --sketch_server_state.")
        if mismatch_ok:
            return  # caller discards the sketch state and keeps weights
        if saved_gen is None:
            # pre-marker checkpoints are UNVERIFIABLE, not known-
            # mismatched: that era could write any sketch_impl/seed with
            # the same (r, c) shapes, so the tables may or may not decode
            # correctly — refuse with wording that says so
            raise ValueError(
                f"checkpoint {path} predates sketch-generation markers, "
                "so its momentum/error tables cannot be verified against "
                f"the current construction {expect_gen!r} (the writing "
                "run's sketch_impl/seed were not recorded). Pass "
                "--resume_unverified to DISCARD the sketch state and "
                "continue from the weights.")
        raise ValueError(
            f"checkpoint sketch generation {saved_gen!r} does not match "
            f"the current construction {expect_gen!r}: the saved "
            "momentum/error tables would decode under the wrong shifts. "
            "Re-create the run, or pass --resume_unverified to DISCARD "
            "the sketch state and continue from the weights.")

    @staticmethod
    def _check_async_gen(saved_gen, expect_gen: str, mismatch_ok: bool,
                         path: str) -> None:
        """Async-aggregation vintage check (marker format:
        cv_train.setup_checkpointing). Only the missing-marker case is
        fatal — a checkpoint written before async buffered aggregation
        (or by a synchronous run) records no buffer/commit ledger, so an
        async resume cannot verify what it is continuing. A marker that
        merely differs (other discount/goal parameters) is a stderr
        warning: commits are atomic, the buffer is flushed at every
        epoch boundary, and any non-empty restored buffer is loudly
        restarted by core/async_agg.reconcile_resumed_state — nothing
        can double-count."""
        if saved_gen == expect_gen:
            return
        if saved_gen is None:
            if mismatch_ok:
                return  # caller resumes with a fresh, empty buffer
            raise ValueError(
                f"checkpoint {path} predates async buffered aggregation "
                "(it carries no async_gen marker): the resume cannot "
                "verify the buffer state or commit ledger this "
                f"--async_agg run ({expect_gen!r}) would continue. Pass "
                "--resume_unverified to resume with a FRESH, EMPTY "
                "buffer — that is safe (commits are atomic, nothing "
                "double-counts); the async commit counter restarts.")
        print(f"WARNING: async-aggregation parameters changed "
              f"({saved_gen!r} -> {expect_gen!r}); resuming anyway — the "
              "buffer is committed/flushed atomically, so only future "
              "merges use the new discount", file=sys.stderr)
