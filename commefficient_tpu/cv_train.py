"""CV experiment driver: federated ResNets on CIFAR10/100, FEMNIST, ImageNet.

Parity target: reference CommEfficient/cv_train.py (421 LoC) — same flag
surface, same five modes, same epoch loop shape (fractional epochs, skip
underfull rounds, NaN abort, per-epoch TableLogger/TSV rows with train/test
loss+acc and simulated per-client down/up MiB, end-of-run checkpoint),
driven by the same triangular LR schedule (0 -> lr_scale @ pivot_epoch -> 0).

Run:  python -m commefficient_tpu.cv_train --dataset_name CIFAR10 \
          --model ResNet9 --mode sketch --error_type virtual ...
"""

from __future__ import annotations

import math
import os
import sys
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu import models
from commefficient_tpu.config import (FedConfig, enable_compilation_cache,
                                      num_classes_of_dataset, parse_args)
from commefficient_tpu.core import FedRuntime, RoundPipeline
from commefficient_tpu.data import (
    FedSampler,
    ValSampler,
    get_dataset,
    transforms_for,
)
from commefficient_tpu.data.device_store import make_device_store
from commefficient_tpu.data.fed_sampler import mask_blocked
from commefficient_tpu.faults import maybe_fault
from commefficient_tpu.losses import make_cv_loss
from commefficient_tpu.telemetry import (ProfilerWindow, UtilizationTracker,
                                         layer_signals_to_host,
                                         signals_to_host, tracing)
from commefficient_tpu.telemetry import maybe_create as make_telemetry
from commefficient_tpu.telemetry.clients import (client_stats_to_host,
                                                 make_ledger)
from commefficient_tpu.telemetry.health import AnomalyMonitor, FlightRecorder
from commefficient_tpu.utils import (
    PiecewiseLinear,
    TableLogger,
    TSVLogger,
    Timer,
    make_logdir,
)


def fixup_lr_multiplier(params, unravel_shape_ref: jax.Array) -> jax.Array:
    """Per-parameter LR multipliers for Fixup models: 0.1 on scalar
    bias/scale params, 1.0 elsewhere (reference param groups,
    cv_train.py:361-371 + FedOptimizer.get_lr, fed_aggregator.py:411-427)."""
    flat_paths = jax.tree_util.tree_flatten_with_path(params)[0]
    pieces = []
    for path, leaf in flat_paths:
        names = "/".join(str(getattr(p, "key", p)) for p in path)
        mult = 0.1 if ("bias" in names or "scale" in names) else 1.0
        pieces.append(np.full(int(np.prod(leaf.shape)), mult, np.float32))
    vec = np.concatenate(pieces)
    assert vec.size == unravel_shape_ref.size
    return jnp.asarray(vec)


def build_model(cfg: FedConfig, num_classes: int):
    kwargs = {"num_classes": num_classes}
    if cfg.do_test:
        # tiny model for the smoke path (reference cv_train.py:329-336)
        kwargs["channels"] = {"prep": 1, "layer1": 1, "layer2": 1,
                              "layer3": 1}
    ctor = models.get_model(cfg.model)
    if cfg.model == "ResNet9":
        kwargs["do_batchnorm"] = cfg.do_batchnorm
    elif cfg.model != "FixupResNet9":
        kwargs.pop("channels", None)
    return ctor(**kwargs)


def build_mesh(cfg: FedConfig):
    """Honor --mesh_shape/--mesh_axes (TPU-native flags): returns a Mesh or
    None for plain single-device jit."""
    if not cfg.mesh_shape:
        return None
    from commefficient_tpu.parallel import make_mesh
    mesh = make_mesh(cfg.mesh_shape, cfg.mesh_axes)
    if mesh is not None:
        n = mesh.shape[mesh.axis_names[0]]
        if cfg.num_workers % n != 0:
            raise ValueError(
                f"--num_workers {cfg.num_workers} must be divisible by the "
                f"mesh axis size {n}")
    return mesh


def setup_checkpointing(cfg: FedConfig, runtime: FedRuntime, name: str):
    """Shared --checkpoint/--checkpoint_every/--resume wiring.
    Returns (ckpt_mgr_or_None, start_epoch, restored_state_or_None,
    resume_info). ``resume_info`` is None for a fresh start; on resume
    it carries the round-granular position plus everything the epoch
    loop needs to continue EXACTLY — {"round_in_epoch": rounds already
    trained in start_epoch (0 for epoch-cadence checkpoints),
    "global_round", "ledgers": the host-ledger sidecar
    (core/preempt.collect_ledger_state), "checkpoint": the restored
    generation, "fallbacks": integrity fallbacks the restore performed
    (for `fault` telemetry)}."""
    if not (cfg.do_checkpoint or cfg.do_resume or cfg.checkpoint_every):
        return None, 0, None, None
    # use the runtime's RESOLVED config from here on: num_cols may have
    # been auto-sized at runtime init (config.auto_num_cols), and the
    # sketch-generation marker below must describe the tables actually
    # built — a marker computed from the caller's pre-runtime copy would
    # let a geometry-mismatched resume slip past the guard
    cfg = runtime.cfg
    from commefficient_tpu.checkpoint import (CheckpointManager,
                                              params_fingerprint)
    mgr = CheckpointManager(os.path.join(cfg.checkpoint_path, name),
                            sharded=cfg.checkpoint_sharded)
    fp = params_fingerprint(runtime.unravel(runtime.initial_weights))
    # sketch state (Vvelocity/Verror tables) is only meaningful under the
    # EXACT sketch construction that encoded it: record a generation
    # marker so a resume under different shifts/signs (e.g. the r3 change
    # to 1024-aligned shifts for aligned num_cols) refuses instead of
    # decoding the tables into garbage
    sketch_gen = None
    if cfg.mode == "sketch":
        sketch_gen = (f"{cfg.sketch_impl}-"
                      + ("aligned1024" if (cfg.sketch_impl == "circ"
                                           and cfg.num_cols % 1024 == 0)
                         else "v1")
                      + f"-{cfg.num_rows}x{cfg.num_cols}-{cfg.sketch_seed}"
                      # dense pre-image server state stores (d,) buffers,
                      # not tables — a cross-state resume must refuse
                      + ("-densestate"
                         if cfg.sketch_server_state == "dense" else ""))
    # async-aggregation vintage marker: records that (and how) this run
    # buffers, so a resume can refuse an unverifiable ledger BEFORE any
    # state is materialized (see checkpoint._check_async_gen). Written as
    # None by synchronous runs — absent and None are the same vintage.
    async_gen = None
    if cfg.async_agg:
        async_gen = (f"v1-{cfg.staleness_discount}"
                     f"-a{cfg.staleness_alpha}"
                     f"-M{cfg.buffer_goal}-K{cfg.max_inflight}")
    mgr.default_meta = {"params_fingerprint": fp, "sketch_gen": sketch_gen,
                        "async_gen": async_gen}
    if cfg.do_resume:
        # the sketch-generation marker is checked against the checkpoint's
        # META (inside restore_latest) BEFORE any state is materialized —
        # in particular a table-state checkpoint resumed under
        # --sketch_server_state dense fails with the layout explanation
        # instead of a raw array-shape error mid-load. The async marker
        # is checked the same way: a pre-async checkpoint resumed into an
        # --async_agg run refuses with the buffer-ledger explanation
        # unless --resume_unverified opts into a fresh, empty buffer
        restored, meta = mgr.restore_latest(
            sharding=runtime._state_sharding, expect_fingerprint=fp,
            allow_missing_fingerprint=cfg.resume_unverified,
            d_pad=runtime.d_pad, num_clients=runtime.num_clients,
            d_row_pad=runtime.d_row_pad,
            expect_sketch_gen=sketch_gen,
            sketch_mismatch_ok=cfg.resume_unverified,
            expect_async_gen=async_gen,
            async_mismatch_ok=cfg.resume_unverified)
        if restored is not None:
            saved_gen = meta.get("sketch_gen")
            if saved_gen != sketch_gen and sketch_gen is not None:
                # only reachable under --resume_unverified (same-layout
                # mismatch). Discard-and-continue: fresh tables, weights
                # kept — resuming with mismatched tables would silently
                # decode garbage every round
                restored = restored.replace(
                    Vvelocity=jnp.zeros_like(restored.Vvelocity),
                    Verror=jnp.zeros_like(restored.Verror))
                print("WARNING: sketch generation changed "
                      f"({saved_gen!r} -> {sketch_gen!r}); momentum/error "
                      "tables RESET, resuming from weights only",
                      file=sys.stderr)
            if runtime._signals_shadow and restored.sig_Verror is None:
                # checkpoints written before the --signals_exact shadow
                # accumulators existed (or with signals off) restore
                # None here; re-zero them so the topk_overlap signal
                # stays LIVE on the resumed run — the shadow (not the
                # run) restarts from zero, as core/state.py documents
                zeros = jnp.zeros((runtime.cfg.grad_size,), jnp.float32)
                restored = restored.replace(sig_Vvelocity=zeros,
                                            sig_Verror=jnp.zeros_like(zeros))
            elif restored.sig_Verror is not None \
                    and not runtime._signals_shadow:
                # the reverse direction: a --signals_exact checkpoint
                # resumed WITHOUT the flag would otherwise thread the
                # dead dense shadow pair (2 x d fp32 — ~1 GB at GPT-2
                # scale) through every round and future checkpoint;
                # drop it so the state matches this runtime's template
                restored = restored.replace(sig_Vvelocity=None,
                                            sig_Verror=None)
            # --defense normclip rolling reference: a checkpoint written
            # before it existed (or with a different window) re-inits it
            # to NaN — the clip reference (not the run) restarts cold,
            # falling back to the resumed rounds' own medians; a ring
            # resumed into a run without normclip is dropped
            ring_n = (runtime.cfg.defense_window
                      if runtime._defense_ring else None)
            cur_ring = restored.defense_ref
            if ring_n is not None and (cur_ring is None
                                       or cur_ring.shape[0] != ring_n):
                restored = restored.replace(defense_ref=jnp.full(
                    (ring_n,), jnp.nan, jnp.float32))
            elif ring_n is None and cur_ring is not None:
                restored = restored.replace(defense_ref=None)
            # async buffer reconciliation (core/async_agg.py): a missing
            # buffer initializes EMPTY, a NON-EMPTY one (mid-epoch
            # postmortem) is LOUDLY restarted — the epoch replays from
            # its boundary, so restoring the buffer would double-count
            # its cohorts; and an async checkpoint resumed synchronously
            # drops the fields to match this runtime's template
            from commefficient_tpu.core.async_agg import \
                reconcile_resumed_state
            restored, async_msgs = reconcile_resumed_state(restored,
                                                           runtime)
            for m in async_msgs:
                print(f"WARNING: {m}", file=sys.stderr)
            start = int(meta.get("epoch", 0))
            # round-granular position (schema: CheckpointManager.save) —
            # epoch-cadence checkpoints sit at round 0, a preempt-tagged
            # generation mid-epoch carries the rounds already trained so
            # the epoch loop can rebuild the SAME (seed, epoch) sampler
            # and skip exactly that many rounds (RoundPipeline skip=)
            start_round = int(meta.get("round_in_epoch", 0))
            resume_info = {
                "round_in_epoch": start_round,
                "global_round": int(meta.get("global_round", -1)),
                "ledgers": meta.get("ledgers"),
                "checkpoint": mgr._path(start, start_round,
                                        meta.get("tag")),
                "fallbacks": list(mgr.restore_fallbacks),
            }
            print(f"resumed from epoch {start}"
                  + (f" + {start_round} rounds (preempt checkpoint)"
                     if start_round else ""))
            return mgr, start, restored, resume_info
    return mgr, 0, None, None


def build_datasets(cfg: FedConfig):
    ds_cls = get_dataset(cfg.dataset_name)
    kw = {}
    if cfg.dataset_name in ("CIFAR10", "CIFAR100", "ImageNet"):
        kw["synthetic_per_class"] = cfg.synthetic_per_class
    if cfg.synthetic_hard:
        # the flag is a CIFAR synthetic-GENERATOR knob; on any config
        # where the generator would not run, silently proceeding would
        # also silently disable train augmentation below — fail fast
        if cfg.dataset_name not in ("CIFAR10", "CIFAR100"):
            raise ValueError(
                "--synthetic_hard is a CIFAR synthetic-generator knob; "
                f"it does nothing for {cfg.dataset_name}")
        if ds_cls._has_real_source(cfg.dataset_dir):
            raise ValueError(
                f"--synthetic_hard set but real data exists under "
                f"{cfg.dataset_dir} (the dataset would train on it and "
                "ignore the flag); remove the flag or point "
                "--dataset_dir elsewhere")
    if cfg.dataset_name in ("CIFAR10", "CIFAR100"):
        kw["synthetic_hard"] = cfg.synthetic_hard
        kw["synthetic_label_noise"] = cfg.synthetic_label_noise
    # the hard synthetic regime's class evidence is per-prototype-pixel:
    # random-crop/flip augmentation scrambles it and training flatlines
    # at chance (same reason tests/test_learning.py trains its synthetic
    # runs un-augmented), so hard-mode runs train on the normalize-only
    # transform; --no_augment requests the same standalone (any
    # per-pixel-prototype synthetic regime, e.g. EMNIST's).
    # cfg.no_augment is already normalized to include synthetic_hard.
    train_transform = transforms_for(
        cfg.dataset_name, train=not cfg.no_augment, seed=cfg.seed)
    if cfg.do_test:
        kw["synthetic"] = True
    train_ds = ds_cls(cfg.dataset_dir, train=True, do_iid=cfg.do_iid,
                      num_clients=cfg.num_clients,
                      transform=train_transform, **kw)
    val_ds = ds_cls(cfg.dataset_dir, train=False,
                    transform=transforms_for(cfg.dataset_name, False), **kw)
    return train_ds, val_ds


def run_validation(runtime: FedRuntime, state, val_ds, cfg: FedConfig,
                   val_store=None):
    """Validation sweep. With a DeviceStore, every batch is gathered on
    device and the per-batch sums accumulate on device — exactly one host
    fetch for the whole sweep (host<->device latency on this runtime is
    ~170 ms per transfer, see data/device_store.py)."""
    acc_sums = None
    host_sums = [0.0, 0.0, 0.0]
    for idx, mask in ValSampler(len(val_ds), cfg.valid_batch_size):
        if val_store is not None:
            batch = val_store.round_batch(idx, None)
        else:
            batch = val_ds.gather(idx)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
        results, n_valid = runtime.val(state, batch, jnp.asarray(mask))
        contrib = jnp.stack([results[0] * n_valid, results[1] * n_valid,
                             n_valid])
        acc_sums = contrib if acc_sums is None else acc_sums + contrib
        if cfg.do_test:
            break
    if acc_sums is not None:
        host_sums = np.asarray(acc_sums)
    total = max(float(host_sums[2]), 1.0)
    return float(host_sums[0]) / total, float(host_sums[1]) / total


def make_writer(cfg: FedConfig, logdir: Optional[str] = None):
    """TensorBoard writer when --tensorboard is set (reference utils.py:51-64
    + cv_train.py:407-411); gated on torch's SummaryWriter being available.
    ``logdir`` shares the run directory with telemetry — make_logdir
    timestamps at second resolution, so two independent calls can split
    one run's artifacts across sibling directories."""
    if not cfg.use_tensorboard:
        return None
    try:
        from torch.utils.tensorboard import SummaryWriter
    except Exception:
        print("WARNING: --tensorboard set but SummaryWriter unavailable")
        return None
    return SummaryWriter(log_dir=logdir or make_logdir(cfg))


def train(cfg: FedConfig, runtime: FedRuntime, state, train_ds, val_ds,
          lr_mult: Optional[jax.Array] = None, loggers=(), timer=None,
          ckpt_mgr=None, start_epoch: int = 0, writer=None, schedule=None,
          telemetry=None, model_flops_per_round: Optional[float] = None,
          resume_info=None):
    timer = timer or Timer()
    # rounds already trained inside start_epoch (round-granular resume:
    # a preempt-tagged checkpoint written mid-epoch; 0 everywhere else)
    start_round = int((resume_info or {}).get("round_in_epoch", 0))
    # profiler window over --profile_rounds (telemetry/profiling.py);
    # replaces the window previously hardcoded to rounds 2-4 of this
    # driver only
    prof = ProfilerWindow(cfg.profile_dir, cfg.profile_rounds)
    # span tracer + MFU/starvation accounting (telemetry/tracing.py,
    # telemetry/utilization.py): only installed when a telemetry stream
    # exists — with --no_telemetry the process-global tracer stays the
    # NullTracer and every span site is a shared no-op context manager
    tracer = util = None
    monitor = recorder = ledger = None
    if telemetry is not None:
        tracer = tracing.install()
        util = UtilizationTracker(telemetry, peak_flops=cfg.peak_flops,
                                  peak_hbm_gbps=cfg.peak_hbm_gbps,
                                  watcher=telemetry.watcher(),
                                  # schema v7: the round's mesh topology,
                                  # so per-chip throughput normalizes
                                  # from the stream alone
                                  n_devices=(runtime.mesh.size
                                             if runtime.mesh is not None
                                             else 1),
                                  mesh_shape=(list(runtime.mesh.shape
                                                   .values())
                                              if runtime.mesh is not None
                                              else None))
        if model_flops_per_round:
            # analytic MFU numerator (gpt2_train passes one: XLA's cost
            # analysis under-counts scanned rounds, models/gpt2.py)
            util.set_flops_per_round(model_flops_per_round)
        # online anomaly monitor (telemetry/health.py): fed every
        # monitored event the stream writes (set_monitor forwarding);
        # under --alert_action checkpoint/abort the flight recorder
        # snapshots state + recent events on the FIRST fired rule
        monitor = AnomalyMonitor(telemetry, action=cfg.alert_action,
                                 window=cfg.alert_window,
                                 z_thresh=cfg.alert_zscore)
        telemetry.set_monitor(monitor)
        if cfg.alert_action in ("checkpoint", "abort"):
            recorder = FlightRecorder(telemetry.logdir, telemetry)
        if cfg.client_stats:
            # host-side participation accounting over the whole client
            # universe — observes the sampler's (host-resident) ids, so
            # it costs no device traffic and runs EVERY round. The
            # backing is policy-selected (telemetry/clients.make_ledger):
            # exact dict for small universes, bounded-memory sketches
            # (telemetry/population.py) at population scale
            ledger = make_ledger(train_ds.num_clients,
                                 cfg.population_sketch)
    # async buffered aggregation (core/async_agg.py): the round splits
    # into dispatch-time cohort compute and buffer-goal commits; the
    # scenario engine (data/scenarios.py) decides each cohort's
    # latency/dropout/participation deterministically off the global
    # round index. One aggregator for the whole run; the epoch boundary
    # flushes it, so checkpoints never straddle an open buffer.
    async_agg = None
    if cfg.async_agg:
        from commefficient_tpu.core.async_agg import (AsyncAggregator,
                                                      commit_loss)
        from commefficient_tpu.data.scenarios import make_scenario
        async_agg = AsyncAggregator(runtime, scenario=make_scenario(cfg))
        print(f"async aggregation: K={async_agg.max_inflight} in flight, "
              f"commit every M={async_agg.buffer_goal} cohorts, "
              f"{async_agg.discount} staleness discount"
              + ("" if async_agg.scenario is None
                 else f", scenario={cfg.scenario}"))
    # decode overlap (core/pipeline.DecodeOverlapRound): the round runs
    # as separate client and server-decode executables, so a record-
    # cadence metrics sync completes when the CLIENT half finishes and
    # the PS decode of round t executes while this loop (and the input
    # pipeline) stage round t+1. Bit-identical losses (dryrun-asserted);
    # mutually exclusive with --async_agg (config-validated).
    overlap_rt = None
    if cfg.decode_overlap:
        from commefficient_tpu.core.pipeline import DecodeOverlapRound
        overlap_rt = DecodeOverlapRound(runtime)
        print("decode overlap: round split into cohort + decode "
              "executables (server decode runs under round t+1's "
              "staging)")
    # robustness subsystem (core/runtime.py does the in-round work; this
    # loop owns the host half): the quarantine ledger benches/ejects
    # clients whose uploads went nonfinite — the device already zeroed
    # them out of the aggregate, this just stops re-dispatching them —
    # and the schema-v5 `defense` event reports what the defense did
    qledger = None
    if cfg.nonfinite_action == "quarantine":
        from commefficient_tpu.core.quarantine import QuarantineLedger
        qledger = QuarantineLedger(backoff=cfg.quarantine_backoff,
                                   strikes=cfg.quarantine_strikes)
    # ---- preemption / fault-tolerance layer (core/preempt.py) ----
    # restore the host-ledger sidecar a round-granular checkpoint
    # carried: quarantine strikes/benches/ejections (a restart must NOT
    # re-admit known-bad clients), participation coverage, and the
    # anomaly monitor's rolling histories — then announce the resume
    # lineage (and any corrupt-generation fallbacks) into the stream
    from commefficient_tpu.core.preempt import (PreemptGuard,
                                                RoundWatchdog,
                                                collect_ledger_state,
                                                restore_ledger_state,
                                                with_retries)
    if resume_info is not None:
        restore_ledger_state(resume_info.get("ledgers"), qledger=qledger,
                             participation=ledger, monitor=monitor)
        if telemetry is not None:
            for fb in resume_info.get("fallbacks") or ():
                telemetry.fault_event(
                    rnd=-1, kind="corrupt_checkpoint",
                    detail=fb.get("error"), checkpoint=fb.get("path"))

    def _ledger_sidecar():
        return collect_ledger_state(qledger=qledger, participation=ledger,
                                    monitor=monitor, telemetry=telemetry)

    # graceful preemption: the FIRST SIGTERM/SIGINT sets a flag this
    # loop notices at the next round boundary (drain within
    # --preempt_grace: close the pipeline, flush the async pool, write
    # a preempt-tagged round-granular checkpoint, fsync a final fault
    # event, exit 0); a SECOND signal force-exits. Constructed here;
    # INSTALLED (and the watchdog thread started) immediately before
    # the try whose finally reclaims them — an exception in the setup
    # code between must not leak a replaced signal handler or a thread
    guard = PreemptGuard(cfg.preempt_grace)
    # hang watchdog (--watchdog): deadline each round's dispatch+sync at
    # watchdog_mult x the rolling median round time; on expiry fire a
    # critical round_stall alert THROUGH the monitor and record an
    # events-only flight-recorder bundle (never a state fetch — that is
    # the operation that may be hung)
    watchdog = None
    if cfg.watchdog:
        def _on_stall(rnd, elapsed, deadline):
            msg = (f"round {rnd} exceeded its stall deadline: "
                   f"{elapsed:.1f}s > {deadline:.1f}s")
            print(f"WATCHDOG: {msg}", file=sys.stderr)
            if monitor is not None:
                monitor.external_alert(rnd=rnd, rule="round_stall",
                                       metric="round.wall_s",
                                       value=float(elapsed))
            if telemetry is not None:
                telemetry.fault_event(rnd=rnd, kind="round_stall",
                                      detail=msg)
                telemetry.fsync()
            if recorder is not None:
                recorder.record(None, {"rule": "round_stall",
                                       "round": int(rnd),
                                       "elapsed_s": float(elapsed),
                                       "deadline_s": float(deadline)})
    adv_plan = getattr(runtime, "adversary_plan", None)
    defense_on = (cfg.defense != "none" or cfg.adversary != "none"
                  or cfg.nonfinite_action == "quarantine")
    if cfg.adversary != "none" and adv_plan is not None:
        n_adv = int(adv_plan.universe_mask(train_ds.num_clients).sum())
        print(f"adversary injection: {cfg.adversary} on {n_adv}/"
              f"{train_ds.num_clients} clients "
              f"(frac {cfg.adversary_frac}), defense={cfg.defense}, "
              f"nonfinite_action={cfg.nonfinite_action}")
    # device-resident data path: upload the dataset once, gather + augment
    # each round's batch on device, accumulate metrics on device, and fetch
    # once per epoch — a host<->device transfer costs ~170 ms latency on
    # this runtime, so the reference's per-round stream-and-read pattern
    # (cv_train.py:193-229) would dominate the ~50 ms round ~10x. On a
    # mesh the arrays replicate across devices and train batches come out
    # already sharded over the round's client axis.
    train_store = make_device_store(
        train_ds, cfg.dataset_name, True, mesh=runtime.mesh,
        out_shardings=(runtime.batch_sharding()
                       if runtime.mesh is not None else None),
        no_augment=cfg.no_augment)
    val_store = make_device_store(val_ds, cfg.dataset_name, False,
                                  mesh=runtime.mesh)
    if train_store is not None:
        print(f"device-resident data: train "
              f"{train_store.nbytes / 2**20:.0f} MiB"
              + (f", val {val_store.nbytes / 2**20:.0f} MiB"
                 if val_store else ""))
    data_key = jax.random.PRNGKey(cfg.seed ^ 0xDA7A)
    if schedule is None:
        # CV default: the cifar10_fast triangular ramp
        # (reference cv_train.py:393-404)
        schedule = PiecewiseLinear(
            [0.0, cfg.pivot_epoch, float(cfg.num_epochs)],
            [0.0, cfg.lr_scale if cfg.lr_scale is not None else 0.4, 0.0])

    # one sampler per epoch, seeded by (seed, epoch): an interrupted run
    # resumed at epoch E replays exactly the round sequence the
    # uninterrupted run would have used from epoch E on (see checkpoint.py)
    def epoch_sampler(epoch: int) -> FedSampler:
        return FedSampler(train_ds.data_per_client, cfg.num_workers,
                          cfg.local_batch_size,
                          max_client_batch=cfg.max_client_batch,
                          seed=cfg.seed + 7919 * epoch)

    spe = max(epoch_sampler(0).epoch_rounds(), 1)
    total_download_mb = total_upload_mb = 0.0
    # resume: the global counter continues from the EXACT round the
    # checkpoint recorded. epoch_rounds() is an upper bound (a sampler
    # can strand an underfull tail and end an epoch early), so deriving
    # the counter as start_epoch * spe can over-number the resumed
    # rounds — shifting every LR lookup and round-keyed RNG off the
    # uninterrupted trajectory. Pre-meta checkpoints (global_round
    # unrecorded) keep the old derivation.
    resume_global = int((resume_info or {}).get("global_round", -1))
    global_round = (resume_global if resume_global >= 0
                    else start_epoch * spe + start_round)
    rounds_run = 0
    summary = None

    # round input fetch, shared by the pipelined and inline paths
    # (core/pipeline.py): all randomness keys off the GLOBAL round index,
    # so prefetching ahead cannot change what trains
    def _fetch_round(rnd, g_round: int):
        if train_store is not None:
            return train_store.round_batch(
                rnd.idx, jax.random.fold_in(data_key, g_round))
        b = train_ds.gather(rnd.idx)
        return {k: jnp.asarray(v) for k, v in b.items()}

    if cfg.watchdog:
        # the retryable host-side phases (DeviceStore gather dispatch /
        # host gather + device_put) get bounded exponential-backoff
        # retries before the round is declared dead — gated on the
        # watchdog opt-in so the lockstep paths keep strict fail-fast
        def fetch_round(rnd, g_round: int):
            def _note(attempt, err):
                if telemetry is not None:
                    telemetry.fault_event(
                        rnd=g_round, kind="fetch_retry",
                        detail=f"attempt {attempt}: {err}")
            return with_retries(lambda: _fetch_round(rnd, g_round),
                                attempts=3, desc=f"round {g_round} input "
                                "fetch", on_retry=_note)
    else:
        fetch_round = _fetch_round

    if cfg.eval_before_start:
        test_loss, test_acc = run_validation(runtime, state, val_ds, cfg,
                                             val_store=val_store)
        print(f"Test acc at epoch 0: {test_acc:0.4f}")

    def _preempt_drain(state, cur_epoch, r_in_epoch, pipe,
                       existing_ckpt=None):
        """The --preempt_grace drain: reclaim the prefetch thread, flush
        the async pool through the existing epoch-flush path (no open
        buffer ever reaches a checkpoint), write an out-of-cadence
        `preempt`-tagged checkpoint with round-granular meta + the
        host-ledger sidecar, and fsync the stream behind a final
        `fault` event. The caller returns (state, None) and the driver
        process exits 0 — a preemption is an orderly handoff, not a
        failure. The grace budget is ENFORCED: a drain that wedges
        (checkpoint save against a hung device, a flush stuck in a dead
        collective) is force-exited when the remaining budget runs out
        — the resume then falls back to the last durable checkpoint.
        ``existing_ckpt`` names an epoch-cadence checkpoint of the SAME
        state written moments ago (the preemption-during-validation
        case): re-saving multi-GB state inside the grace window would
        only burn the budget, so the drain reuses it."""
        remaining = max(cfg.preempt_grace - (guard.grace_used_s() or 0.0),
                        1.0)
        force_timer = guard.force_exit_after(remaining)
        try:
            return _drain_body(state, cur_epoch, r_in_epoch, pipe,
                               existing_ckpt)
        finally:
            force_timer.cancel()

    def _flush_async(state):
        """Drain the in-flight pool and commit any partial buffer,
        recording each commit — ONE implementation for the epoch
        boundary and the preempt drain, so checkpoints written by
        either always see a closed buffer with identical semantics."""
        if async_agg is None:
            return state
        flush_lr = schedule(global_round / spe)
        flush_lr_arr = (jnp.asarray(flush_lr, jnp.float32)
                        if lr_mult is None else flush_lr * lr_mult)
        state, fcommits = async_agg.flush(state, flush_lr_arr)
        if telemetry is not None:
            for c in fcommits:
                telemetry.async_round_event(rec=c, lr=float(flush_lr),
                                            loss=commit_loss(c),
                                            with_device=True)
        return state

    def _drain_body(state, cur_epoch, r_in_epoch, pipe, existing_ckpt):
        if pipe is not None:
            pipe.close()
        state = _flush_async(state)
        ck_path = None
        if existing_ckpt is not None:
            ck_path = existing_ckpt
        elif ckpt_mgr is not None:
            ck_path = ckpt_mgr.save(
                state, cur_epoch,
                meta={"global_round": int(global_round),
                      "ledgers": _ledger_sidecar()},
                round_in_epoch=r_in_epoch, tag="preempt")
        else:
            print("PREEMPT WARNING: no checkpoint manager configured — "
                  "draining WITHOUT a checkpoint; progress since the "
                  "last save is lost on restart", file=sys.stderr)
        grace = guard.grace_used_s()
        print(f"PREEMPT: drained at epoch {cur_epoch} + {r_in_epoch} "
              f"round(s) (global round {global_round})"
              + (f"; checkpoint {ck_path}" if ck_path else "")
              + (f"; grace used {grace:.1f}s of {cfg.preempt_grace:.0f}s"
                 if grace is not None else ""))
        prof.finalize(lambda: jax.block_until_ready(state.ps_weights))
        if telemetry is not None:
            telemetry.fault_event(rnd=global_round, kind="preempt",
                                  signal=guard.signal_name, grace_s=grace,
                                  checkpoint=ck_path)
            telemetry.span_event(tracer)
            telemetry.write_summary(
                aborted=True, n_rounds=rounds_run,
                total_download_mib=total_download_mb,
                total_upload_mib=total_upload_mb,
                final=telemetry.last_epoch)
            telemetry.fsync()
        return state

    pipe = None
    # arm the preemption layer LAST: the finally below owns handler
    # restoration and thread reclamation, so nothing between creation
    # and here may raise with them live
    guard.install()
    if cfg.watchdog:
        watchdog = RoundWatchdog(_on_stall, mult=cfg.watchdog_mult)
    try:
        for epoch in range(start_epoch, math.ceil(cfg.num_epochs)):
            epoch_fraction = (cfg.num_epochs - epoch
                              if epoch == math.ceil(cfg.num_epochs) - 1 else 1.0)
            ep_sums = None   # device accumulator: [loss*w, acc*w, w, down, up]
            # round input pipeline: the prefetcher owns the fractional-
            # epoch cap (reference cv_train.py:194-196) and the global
            # round numbering; with --no_pipeline it degrades to the same
            # fetch inline (bit-identical rounds, see core/pipeline.py)
            # round-granular resume: the resumed epoch rebuilds its
            # (seed, epoch) sampler and fast-forwards past the rounds
            # the preempt checkpoint already trained (skip=; fetches
            # nothing for them, numbering continues exactly)
            epoch_skip = start_round if epoch == start_epoch else 0
            r_in_epoch = epoch_skip
            pipe = RoundPipeline(
                epoch_sampler(epoch), fetch_round,
                start_round=global_round - epoch_skip,
                max_rounds=(1 if cfg.do_test
                            else int(math.ceil(spe * epoch_fraction))),
                depth=cfg.prefetch_depth, enabled=cfg.pipeline,
                skip=epoch_skip)
            for item in pipe:
                if guard.requested:
                    # graceful preemption: the just-fetched item has NOT
                    # trained — r_in_epoch counts only consumed rounds,
                    # so the resume replays exactly from here
                    state = _preempt_drain(state, epoch, r_in_epoch,
                                           pipe)
                    return state, None
                rnd, batch = item.rnd, item.batch
                global_round = item.global_round
                r_in_epoch += 1
                maybe_fault("pre_round", global_round)
                if qledger is not None:
                    # bench quarantined clients at DISPATCH time (the
                    # prefetched Round is shared state — never mutated):
                    # their slots keep static shapes, contribute no data
                    rnd = mask_blocked(rnd, qledger.blocked(global_round))
                t_loop = time.perf_counter()
                # host_s = what the loop WAITED for this round's input
                # (inline: the fetch itself; pipelined: the queue wait —
                # the prefetch overlap is exactly host_s shrinking)
                host_s = item.wait_s
                lr = schedule(global_round / spe)
                lr_arr = (jnp.asarray(lr, jnp.float32) if lr_mult is None
                          else lr * lr_mult)
                prof.maybe_start(global_round)
                if watchdog is not None:
                    # deadline the dispatch+sync (the phases a hung
                    # collective or wedged transfer actually blocks)
                    watchdog.arm(global_round)
                commits = ()
                if async_agg is not None:
                    # metrics is None for a scenario-dropped cohort (no
                    # compute happened — nothing to record or accumulate)
                    state, metrics, commits = async_agg.step(
                        state, rnd, global_round, batch, lr_arr)
                elif overlap_rt is not None:
                    state, metrics = overlap_rt.round(
                        state, rnd.client_ids, batch, rnd.mask, lr_arr)
                else:
                    state, metrics = runtime.round(
                        state, rnd.client_ids, batch, rnd.mask, lr_arr)
                t_dispatch = time.perf_counter()
                prof.maybe_stop(global_round,
                                lambda: jax.block_until_ready(state.ps_weights))
                every = cfg.telemetry_round_every
                record = (telemetry is not None and every
                          and global_round % every == 0
                          and metrics is not None)
                maybe_fault("mid_round", global_round)
                t_device = t_dispatch
                if record:
                    # each round record costs ONE host sync of the round's
                    # metrics — the price of round-granularity observability
                    # (see config.telemetry_every); the device-side epoch
                    # accumulation below is unchanged either way
                    with tracing.span("device_wait"):
                        jax.block_until_ready(metrics)
                    t_device = time.perf_counter()
                if watchdog is not None:
                    # only synced (record) rounds feed the deadline
                    # history — a dispatch-only duration is not a round
                    # time (see RoundWatchdog.disarm)
                    watchdog.disarm(observe=record)
                if util is not None and metrics is not None:
                    # device_s is only measured on synced (record) rounds;
                    # the tracker treats None as "not measured", not zero.
                    # Scenario-dropped cohorts are not observed at all: no
                    # device work ran, and counting them as rounds would
                    # quietly deflate the window's per-round MFU
                    util.observe_round(
                        host_s=host_s,
                        dispatch_s=t_dispatch - t_loop,
                        device_s=(t_device - t_dispatch) if record
                        else None)
                # ---- untimed tail: every phase boundary above is already
                # captured, so the host fetch + JSONL writes below (and
                # their flush latency) land in NO measured phase — they
                # are visible instead as the telemetry_emit span
                if ledger is not None and metrics is not None:
                    # sampler ids/mask are host arrays: no device fetch.
                    # In async mode the scenario may have masked slots
                    # out of the cohort — observe the EFFECTIVE
                    # participation the aggregator reports, not the
                    # sampler's intent
                    if async_agg is not None:
                        obs_ids, obs_n = metrics["participation"]
                    else:
                        obs_ids = rnd.client_ids
                        obs_n = np.asarray(rnd.mask).sum(axis=1)
                    ledger.observe(global_round, obs_ids, obs_n)
                if qledger is not None and metrics is not None \
                        and metrics.get("client_finite") is not None:
                    # quarantine strikes: ONE (W,)-bool fetch per round —
                    # the documented host-sync price of quarantine mode
                    # (the device zeroing already protected the round)
                    fin = np.asarray(metrics["client_finite"])
                    struck = qledger.observe(
                        global_round, np.asarray(rnd.client_ids), fin)
                    if ledger is not None and struck:
                        # the population ledger's quarantine-strike
                        # heavy-hitter stream: which clients keep
                        # uploading garbage, at any universe size
                        ledger.observe_strikes(struck)
                    for cid in struck:
                        if cid in qledger.ejected:
                            what = "EJECTED (strikes exhausted)"
                        else:
                            what = (f"benched {cfg.quarantine_backoff} "
                                    f"rounds (strike "
                                    f"{qledger.strikes[cid]}/"
                                    f"{qledger.max_strikes})")
                        print(f"QUARANTINE: client {cid} uploaded a "
                              f"nonfinite update at round {global_round}; "
                              f"{what}", file=sys.stderr)
                    if len(qledger.ejected) >= train_ds.num_clients:
                        # every client permanently ejected: no data
                        # source remains, and letting the loop keep
                        # dispatching fully-masked rounds would burn the
                        # whole budget on a silently "successful" run
                        print("QUARANTINE ABORT: all "
                              f"{train_ds.num_clients} clients are "
                              "permanently ejected (nonfinite strikes "
                              "exhausted) — no data remains, TERMINATING")
                        prof.finalize(lambda: jax.block_until_ready(
                            state.ps_weights))
                        if telemetry is not None:
                            telemetry.alert_event(
                                rnd=global_round,
                                rule="quarantine_exhausted",
                                severity="critical",
                                metric="defense.ejected",
                                value=float(len(qledger.ejected)),
                                action=cfg.alert_action)
                            # final residency snapshot, then the bundle:
                            # a quarantine-exhausted postmortem ships the
                            # memory timeline (memory.json) like the
                            # NaN-abort path does
                            telemetry.memory_event("quarantine_exhausted")
                            if recorder is not None:
                                recorder.record(state, {
                                    "rule": "quarantine_exhausted",
                                    "round": int(global_round),
                                    "ejected": len(qledger.ejected)})
                            telemetry.span_event(tracer)
                            telemetry.write_summary(
                                aborted=True, n_rounds=rounds_run + 1,
                                total_download_mib=total_download_mb,
                                total_upload_mib=total_upload_mb,
                                final=telemetry.last_epoch)
                            telemetry.fsync()
                        return state, None
                if record:
                    with tracing.span("telemetry_emit"):
                        res = [np.asarray(r) for r in metrics["results"]]
                        nv = np.asarray(metrics["n_valid"], np.float64)
                        tot = max(float(nv.sum()), 1.0)
                        acc_idx = 1 if len(res) > 1 else 0
                        down_total = up_total = None
                        down_clients = up_clients = None
                        if cfg.track_bytes:
                            # exact per-client byte costs: the round metrics
                            # scatter them at client_ids over (num_clients,)
                            down_all = np.asarray(metrics["download_bytes"])
                            up_all = np.asarray(metrics["upload_bytes"])
                            down_total = float(down_all.sum())
                            up_total = float(up_all.sum())
                            ids = np.asarray(rnd.client_ids)
                            down_clients = [float(x) for x in down_all[ids]]
                            up_clients = [float(x) for x in up_all[ids]]
                        telemetry.round_event(
                            rnd=global_round, epoch=epoch + 1, lr=float(lr),
                            loss=float((res[0] * nv).sum() / tot),
                            acc=float((res[acc_idx] * nv).sum() / tot),
                            n_valid=float(nv.sum()),
                            download_bytes=down_total,
                            upload_bytes=up_total,
                            host_s=host_s,
                            dispatch_s=t_dispatch - t_loop,
                            device_s=t_device - t_dispatch)
                        if metrics.get("signals"):
                            # compression-signal health, same cadence / same
                            # host sync as the round record (signals.py)
                            telemetry.signals_event(
                                rnd=global_round, mode=cfg.mode,
                                signals=signals_to_host(metrics["signals"]),
                                download_bytes=down_total,
                                upload_bytes=up_total,
                                client_download_bytes=down_clients,
                                client_upload_bytes=up_clients)
                        if metrics.get("layer_signals"):
                            # layer-wise attribution (layer_signals.py):
                            # per-group vectors, same cadence — the
                            # group_starvation monitor rule feeds off
                            # this event via the stream forwarding
                            telemetry.layer_signals_event(
                                rnd=global_round, mode=cfg.mode,
                                signal_groups=cfg.signal_groups,
                                groups=runtime.group_spec.names,
                                sizes=runtime.group_spec.sizes,
                                values=layer_signals_to_host(
                                    metrics["layer_signals"]))
                        if metrics.get("client_stats") is not None \
                                and ledger is not None:
                            # per-client population quantiles (device-
                            # reduced, telemetry/clients.py) + the
                            # participation ledger snapshot
                            # async: the scenario may have masked slots
                            # out — count the EFFECTIVE participants
                            # (slots that carried data), matching what
                            # the quantile weights and the ledger saw
                            n_part = (int((np.asarray(obs_n) > 0).sum())
                                      if async_agg is not None
                                      else len(np.asarray(rnd.client_ids)))
                            quantiles = client_stats_to_host(
                                metrics["client_stats"], rnd.client_ids)
                            # the loss-argmax heavy-hitter stream: the
                            # round's worst client id, already computed
                            # on device for the quantile record
                            ledger.observe_loss_argmax(
                                (quantiles.get("loss") or {})
                                .get("argmax_client"))
                            telemetry.client_stats_event(
                                rnd=global_round,
                                n_participants=n_part,
                                quantiles=quantiles,
                                participation=ledger.snapshot(
                                    global_round))
                        if ledger is not None:
                            # population-scale participation summary
                            # (schema v11): the ledger's full universe
                            # view — exact or sketch-estimated, its
                            # `estimated` flag says which; feeds the
                            # coverage_stall / hh_churn monitor rules
                            telemetry.population_event(
                                snapshot=ledger.population_snapshot(
                                    global_round))
                        if defense_on:
                            # schema-v5 defense record: device scalars
                            # (already synced with the metrics above) +
                            # the quarantine ledger + injected counts
                            dd = metrics.get("defense")
                            inj = None
                            if adv_plan is not None:
                                # a hostile slot only INJECTS if it
                                # carries data: inject_adversary skips
                                # zero-datum slots (benched/participation-
                                # masked clients upload nothing), so the
                                # count must too or the stream reports
                                # injection from clients that sat out
                                if async_agg is not None:
                                    ids_a, n_a = metrics["participation"]
                                    slots = metrics.get("adversary_slots")
                                    if slots is None:
                                        slots = adv_plan.slot_mask(
                                            np.asarray(ids_a))
                                    live = np.asarray(n_a) > 0
                                else:
                                    slots = adv_plan.slot_mask(
                                        np.asarray(rnd.client_ids))
                                    live = np.asarray(rnd.mask).any(axis=1)
                                inj = {cfg.adversary: int(
                                    (np.asarray(slots) & live).sum())}
                            telemetry.defense_event(
                                rnd=global_round,
                                defense=cfg.defense,
                                adversary=cfg.adversary,
                                nonfinite_action=cfg.nonfinite_action,
                                device=(signals_to_host(dd) if dd
                                        else {}),
                                quarantine=(qledger.snapshot(global_round)
                                            if qledger is not None
                                            else None),
                                injected=inj)
                        # MFU/starvation over the window since the last
                        # record, and the window's spans — the tail of
                        # this round's trace lands in the next drain
                        util.emit(global_round)
                    telemetry.span_event(tracer)
                if telemetry is not None and commits:
                    # async commit records (schema v4 async_round): the
                    # host-side staleness/discount bookkeeping is free
                    # and emitted for EVERY commit; the device-derived
                    # fields (loss, buffer_n, EF norms) cost a host sync
                    # each, so they ride only the record cadence — off
                    # it they are null, never fake zeros
                    for c in commits:
                        telemetry.async_round_event(
                            rec=c, lr=float(lr),
                            loss=(commit_loss(c) if record else None),
                            with_device=record)
                if record or (telemetry is not None and commits):
                    # ---- alert actions (telemetry/health.py): the
                    # monitor already wrote its alert events while the
                    # records above were emitted (async_round included);
                    # here the driver owns the side effects that need
                    # the live state
                    if recorder is not None:
                        req = monitor.pop_snapshot_request()
                        if req is not None:
                            recorder.record(state, req)
                    if monitor is not None and monitor.abort_requested:
                        last = monitor.alerts[-1]
                        print(f"ALERT ABORT (--alert_action abort): rule "
                              f"{last['rule']} on {last['metric']} at "
                              f"round {last['round']}, TERMINATING")
                        prof.finalize(lambda: jax.block_until_ready(
                            state.ps_weights))
                        telemetry.span_event(tracer)
                        telemetry.write_summary(
                            aborted=True, n_rounds=rounds_run + 1,
                            total_download_mib=total_download_mb,
                            total_upload_mib=total_upload_mb,
                            final=telemetry.last_epoch)
                        telemetry.fsync()
                        return state, None
                if metrics is None:
                    # scenario-dropped cohort: no compute happened, so
                    # there is nothing to count or accumulate
                    if cfg.do_test:
                        break
                    continue
                rounds_run += 1
                if telemetry is not None and rounds_run == 1:
                    # device memory after the first round: weights + server
                    # state + the round's working set are all live by now
                    telemetry.memory_event("round_1")
                # accumulate on device: no host fetch inside the round loop
                w = metrics["n_valid"]
                contrib = jnp.stack([
                    (metrics["results"][0] * w).sum(),
                    (metrics["results"][1] * w).sum(),
                    w.sum(),
                    (metrics["download_bytes"].sum()
                     if cfg.track_bytes else jnp.zeros(())),
                    (metrics["upload_bytes"].sum()
                     if cfg.track_bytes else jnp.zeros(())),
                ])
                ep_sums = contrib if ep_sums is None else ep_sums + contrib
                if cfg.do_test:
                    break

            # reclaim the prefetch thread at the epoch boundary. In the
            # normal case every round was consumed; on the early-exit
            # paths (--test) unconsumed prefetched batches are dropped —
            # a stateful host-transform RNG may have advanced for them,
            # which is fine only because nothing trains on this dataset
            # stream afterwards (see RoundPipeline.close)
            pipe.close()
            # drain the in-flight pool and commit any partial buffer:
            # epochs (and therefore checkpoints, which are written at
            # epoch granularity below) never straddle an open buffer —
            # shared with the preempt drain (_flush_async)
            state = _flush_async(state)
            if util is not None:
                # close the round window at the epoch boundary: the
                # validation sweep below must not dilute the round MFU
                util.emit(global_round)
            if telemetry is not None:
                # residency snapshot at the END of the round phase —
                # the epoch_<n> snapshot below lands after validation,
                # so its delta_peak_bytes attributes validation's
                # high-water growth while this one owns the rounds'
                telemetry.memory_event(f"rounds_{epoch + 1}")
            sums = (np.asarray(ep_sums) if ep_sums is not None
                    else np.zeros(5))
            train_time = timer()
            # NaN abort, checked at the epoch boundary (the reference checks per
            # round, cv_train.py:222-224 — per-round host fetches are what this
            # loop exists to avoid). The device-side flag reports the exact
            # offending round and gates every checkpoint write below, so
            # poisoned state is never persisted.
            nan_round = int(state.nan_round)
            if nan_round >= 0 or np.isnan(sums[0]):
                which = (f"first non-finite update at round {nan_round}"
                         if nan_round >= 0 else f"epoch loss {sums[0]} is NaN")
                print(f"TRAINING DIVERGED ({which}), TERMINATING")
                prof.finalize(lambda: jax.block_until_ready(state.ps_weights))
                if telemetry is not None:
                    # a postmortem's LAST events name what killed the
                    # run: a final critical alert, then the structured
                    # nan_abort — and the flight recorder (when armed)
                    # snapshots the state/events before the return
                    telemetry.alert_event(
                        rnd=nan_round if nan_round >= 0 else global_round,
                        rule="nonfinite_abort", severity="critical",
                        metric="loss", action=cfg.alert_action)
                    # final residency snapshot BEFORE the bundle, so the
                    # postmortem's memory.json timeline ends at the abort
                    telemetry.memory_event("nan_abort")
                    if recorder is not None:
                        recorder.record(state, {
                            "rule": "nonfinite_abort", "reason": which,
                            "round": int(nan_round)})
                    # structured divergence diagnostic: which round went
                    # non-finite, under what mode/clip/sketch config, and the
                    # last records known finite — instead of only the bare
                    # console line above
                    telemetry.nan_abort(nan_round=nan_round, reason=which,
                                        cfg=runtime.cfg)
                    telemetry.span_event(tracer)  # keep the partial trace
                    telemetry.write_summary(
                        aborted=True, n_rounds=rounds_run,
                        total_download_mib=total_download_mb,
                        total_upload_mib=total_upload_mb,
                        final=telemetry.last_epoch)
                    # never hand a truncated stream to the postmortem:
                    # everything above must survive the process dying
                    # right after this return (BENCH_r02 lesson, fsync'd)
                    telemetry.fsync()
                return state, None
            total = max(float(sums[2]), 1.0)
            train_loss = float(sums[0]) / total
            train_acc = float(sums[1]) / total
            download_mb = float(sums[3]) / (1024 * 1024)
            upload_mb = float(sums[4]) / (1024 * 1024)
            total_download_mb += download_mb
            total_upload_mb += upload_mb

            with tracing.span("validation"):
                test_loss, test_acc = run_validation(
                    runtime, state, val_ds, cfg, val_store=val_store)
            test_time = timer()

            summary = {
                "epoch": epoch + 1,
                "lr": schedule(global_round / spe),
                "train_time": train_time,
                "train_loss": train_loss,
                "train_acc": train_acc,
                "test_loss": test_loss,
                "test_acc": test_acc,
                "down (MiB)": round(download_mb),
                "up (MiB)": round(upload_mb),
                "total_time": timer.total_time,
            }
            for logger in loggers:
                logger.append(summary)
            if telemetry is not None:
                telemetry.epoch_event(summary, test_time=test_time)
                telemetry.memory_event(f"epoch_{epoch + 1}")
                telemetry.span_event(tracer)  # incl. the validation span
                # rules fired by the epoch-boundary utilization flush
                # (e.g. mfu_cliff) get their side effects here, not a
                # full record-cadence later
                if recorder is not None:
                    req = monitor.pop_snapshot_request()
                    if req is not None:
                        recorder.record(state, req)
                if monitor is not None and monitor.abort_requested:
                    last = monitor.alerts[-1]
                    print(f"ALERT ABORT (--alert_action abort): rule "
                          f"{last['rule']} on {last['metric']} at round "
                          f"{last['round']}, TERMINATING")
                    telemetry.write_summary(
                        aborted=True, n_rounds=rounds_run,
                        total_download_mib=total_download_mb,
                        total_upload_mib=total_upload_mb,
                        final=telemetry.last_epoch)
                    telemetry.fsync()
                    return state, None
            if writer is not None:
                # reference scalar set (cv_train.py:150-158)
                writer.add_scalar("Loss/train", train_loss, epoch)
                writer.add_scalar("Loss/test", test_loss, epoch)
                writer.add_scalar("Acc/train", train_acc, epoch)
                writer.add_scalar("Acc/test", test_acc, epoch)
                writer.add_scalar("Time/train", train_time, epoch)
                writer.add_scalar("Time/test", test_time, epoch)
                writer.add_scalar("Time/total", timer.total_time, epoch)
                writer.add_scalar("Lr", summary["lr"], epoch)
            epoch_ck_path = None
            if (ckpt_mgr is not None and cfg.checkpoint_every
                    and (epoch + 1) % cfg.checkpoint_every == 0):
                # epoch-cadence checkpoints carry the SAME round-
                # granular meta + host-ledger sidecar as the preempt
                # path: even an epoch-granular resume must not silently
                # un-bench/un-eject quarantined clients or reset the
                # monitor's rolling envelopes
                epoch_ck_path = ckpt_mgr.save(
                    state, epoch + 1,
                    meta={"summary": summary,
                          "global_round": int(global_round),
                          "ledgers": _ledger_sidecar()})
                if telemetry is not None:
                    # the third phase of the residency attribution:
                    # delta_peak_bytes here is the checkpoint writer's
                    # high-water contribution (host-side gathers of a
                    # sharded state can spike device residency too)
                    telemetry.memory_event(f"checkpoint_{epoch + 1}")
            if guard.requested:
                # preemption landed during validation/checkpointing:
                # drain at the epoch boundary (epoch+1 complete, 0
                # rounds into the next). A cadence checkpoint written
                # just above holds this exact state (the async pool was
                # flushed BEFORE it) — reuse it instead of re-saving
                # inside the grace window
                state = _preempt_drain(state, epoch + 1, 0, pipe,
                                       existing_ckpt=epoch_ck_path)
                return state, None
            if cfg.do_test:
                break

    except BaseException:
        # an unhandled crash (OOM, data error, Ctrl-C) inside the
        # profiler window must still close the process-global trace
        # (the rounds captured so far become a partial trace) —
        # mirrors bench_common.timed_rounds' guard
        prof.abort()
        raise
    finally:
        # reclaim the prefetch thread however the loop ends (abort
        # returns, NaN aborts, exceptions) — close() is idempotent, so
        # the epoch-boundary close above makes this a no-op normally
        if pipe is not None:
            pipe.close()
        # restore the process's previous signal handlers and reclaim
        # the watchdog thread on every exit path — no leaked handlers
        # or threads, whatever killed the loop
        guard.uninstall()
        if watchdog is not None:
            watchdog.close()
        # release the process-global span tracer however the loop ends
        # (the tail below only DRAINS the local tracer object, which
        # stays valid after uninstall)
        if tracer is not None:
            tracing.uninstall()
    # a window whose STOP lies beyond the last round (or that a --test /
    # fractional-epoch break cut short) still yields its partial trace
    prof.finalize(lambda: jax.block_until_ready(state.ps_weights))
    n_clients = train_ds.num_clients
    print(f"Total Download (MiB): {total_download_mb:0.2f}")
    print(f"Total Upload (MiB): {total_upload_mb:0.2f}")
    print(f"Avg Download Per Client: {total_download_mb / n_clients:0.2f}")
    print(f"Avg Upload Per Client: {total_upload_mb / n_clients:0.2f}")
    if telemetry is not None:
        telemetry.span_event(tracer)  # any spans since the last epoch
        telemetry.write_summary(aborted=False, n_rounds=rounds_run,
                                total_download_mib=total_download_mb,
                                total_upload_mib=total_upload_mb,
                                final=telemetry.last_epoch)
    return state, summary


def main(argv=None):
    cfg = parse_args(argv, default_lr=0.4)
    enable_compilation_cache(cfg)
    np.random.seed(cfg.seed)
    if cfg.do_test:
        # shrink sketch to smoke size (reference cv_train.py:329-336)
        cfg = cfg.replace(num_cols=10, num_rows=1, k=10)

    timer = Timer()
    train_ds, val_ds = build_datasets(cfg)
    cfg = cfg.replace(num_clients=train_ds.num_clients)

    num_classes = num_classes_of_dataset(
        cfg.finetuned_from if cfg.do_finetune else cfg.dataset_name)
    model = build_model(cfg, num_classes)

    sample = train_ds.gather(np.zeros((1,), np.int64))
    init_x = jnp.asarray(sample["image"])
    params = model.init(jax.random.PRNGKey(cfg.seed), init_x)

    # stateless batch-norm eval caveat (models/layers.py BatchStatNorm):
    # small eval batches compound stat noise with DEPTH — measured
    # chance-level val accuracy at depth 50 with batch 8 where batch 256
    # tracks train accuracy. Warn whenever a batch-normed model will
    # evaluate on small batches.
    bsn_scopes = set()
    for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]:
        keys = [str(getattr(p, "key", p)) for p in path]
        for i, k in enumerate(keys):
            if "BatchStatNorm" in k:
                bsn_scopes.add("/".join(keys[: i + 1]))
                break
    n_bsn = len(bsn_scopes)
    # threshold between ResNet-9's 8 norm layers (measured robust at
    # batch 8) and the 20+ of the torchvision-family depth-18+ ports
    if n_bsn > 10 and cfg.valid_batch_size < 64:
        print(f"WARNING: {cfg.model} stacks {n_bsn} batch-stat norm "
              f"layers and --valid_batch_size {cfg.valid_batch_size} < "
              "64: eval batches normalize by their OWN statistics, and "
              "small-batch stat noise compounds with depth (measured: "
              "chance-level val accuracy at depth 50 / batch 8 where "
              "batch 256 tracks train). Raise --valid_batch_size.",
              file=sys.stderr)

    frozen = None
    if cfg.do_finetune:
        params, frozen = load_finetune_params(cfg, model, params)

    loss_fn = make_cv_loss(model, cfg.compute_dtype, frozen_params=frozen)
    runtime = FedRuntime(cfg, params, loss_fn,
                         num_clients=train_ds.num_clients,
                         mesh=build_mesh(cfg))
    state = runtime.init_state()

    lr_mult = None
    if cfg.model.startswith("Fixup"):
        print("using fixup learning rates")
        lr_mult = fixup_lr_multiplier(params, runtime.initial_weights)

    ckpt_mgr, start_epoch, restored, resume_info = setup_checkpointing(
        cfg, runtime, cfg.model)
    if restored is not None:
        state = restored

    print(f"Finished initializing in {timer():.2f} seconds")
    # ONE logdir for the whole run: telemetry and the tensorboard writer
    # must share it (make_logdir timestamps at second resolution — two
    # calls can split the artifacts across sibling directories).
    # --logdir pins it: a resumed run pointed at its predecessor's
    # directory APPENDS to the stream behind a `resume` lineage record
    logdir = (cfg.logdir or make_logdir(cfg)
              if cfg.telemetry or cfg.use_tensorboard else None)
    # telemetry opens against the runtime's RESOLVED config (grad_size
    # filled in, num_cols auto-sized) so the manifest records the run
    # that actually executes
    telemetry = make_telemetry(
        runtime.cfg, "cv_train", logdir=logdir,
        resume_info=(None if resume_info is None else {
            "round": resume_info["global_round"],
            "epoch": start_epoch,
            "checkpoint": resume_info["checkpoint"]}))
    if telemetry is not None:
        telemetry.instrument(runtime)
        telemetry.memory_event("init")
    tsv = TSVLogger()
    try:
        state, summary = train(cfg, runtime, state, train_ds, val_ds,
                               lr_mult=lr_mult, loggers=(TableLogger(), tsv),
                               timer=timer, ckpt_mgr=ckpt_mgr,
                               start_epoch=start_epoch,
                               writer=make_writer(cfg, logdir=logdir),
                               telemetry=telemetry,
                               resume_info=resume_info)
    finally:
        if telemetry is not None:
            telemetry.close()
    print(tsv)

    if cfg.do_checkpoint and summary is not None:
        os.makedirs(cfg.checkpoint_path, exist_ok=True)
        path = os.path.join(cfg.checkpoint_path, cfg.model + ".npz")
        np.savez(path, ps_weights=np.asarray(runtime.flat_weights(state)))
        print(f"saved checkpoint to {path}")
    return summary


def load_finetune_params(cfg: FedConfig, model, params):
    """Finetune mode (reference cv_train.py:342-352, 377-384): load saved
    weights, then split the pytree into the trainable head and the frozen
    backbone, so the federated vector covers only the head."""
    path = os.path.join(cfg.finetune_path, cfg.model + ".npz")
    loaded = np.load(path)["ps_weights"]
    from commefficient_tpu.ops import ravel_params
    _, unravel = ravel_params(params)
    full = unravel(jnp.asarray(loaded))
    head_keys = [k for k in full["params"]
                 if k in ("head", "classifier", "fc")]
    assert head_keys, "no recognisable head to finetune"
    num_new = num_classes_of_dataset(cfg.dataset_name)
    # re-init the head at the new class count (reference
    # finetune_parameters, models/resnet9.py:105-113)
    sample_head = params["params"][head_keys[0]]
    new_head = jax.tree.map(
        lambda t: jnp.zeros(t.shape[:-1] + (num_new,), t.dtype), sample_head)
    trainable = {"params": {head_keys[0]: new_head}}
    frozen = {"params": {k: v for k, v in full["params"].items()
                         if k not in head_keys}}
    return trainable, frozen


if __name__ == "__main__":
    main()
