"""Reference-API compatibility facade: ``FedModel`` / ``FedOptimizer``.

The reference's user surface (SURVEY.md §1 L4) is

    model = FedModel(torch_model, compute_loss_train, args, compute_loss_val)
    opt   = FedOptimizer(torch.optim.SGD(model.parameters(), lr=1), args)
    ...
    loss, acc, download, upload = model(batch)   # train step
    opt.step()
    model.finalize()

This module reproduces that shape over the functional `FedRuntime` so driver
code written against the reference ports with minimal edits. Differences
dictated by the functional design:

- the model is a Flax module + loss closure (see losses.py) instead of a
  torch ``nn.Module``;
- the reference splits each step across ``model(batch)`` (client compute +
  NCCL reduce, fed_aggregator.py:213-335) and ``opt.step()`` (server update,
  fed_aggregator.py:429-458). Because the scheduler advances the LR *before*
  ``model(batch)`` (cv_train.py:198), the LR of the round is already known
  at call time — so the facade runs the whole fused round inside
  ``__call__`` and ``opt.step()`` is bookkeeping-only. Observable behavior
  (returned metrics, weight trajectory) is identical.
- ``batch`` is the reference wire format: a dict of arrays over a flat
  datum axis whose ``client_id`` entry gives each datum's client (the
  reference uses tuple-position-0, fed_dataset.py:95; val marks -1).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime


def split_by_client(client_ids: np.ndarray, batch: Dict[str, np.ndarray],
                    num_workers: int, batch_size: int):
    """Reference ``_call_train`` batch split (fed_aggregator.py:218-224):
    group the flat batch by unique client id into the static
    (num_workers, batch_size) layout + mask."""
    uniq = np.unique(client_ids)
    if len(uniq) < num_workers:
        raise ValueError(
            f"round has {len(uniq)} clients < num_workers={num_workers} "
            "(the reference driver skips such batches, cv_train.py:205-219)")
    uniq = uniq[:num_workers]
    out_ids = np.zeros(num_workers, np.int64)
    masks = np.zeros((num_workers, batch_size), bool)
    gathered = {k: np.zeros((num_workers, batch_size) + v.shape[1:],
                            v.dtype) for k, v in batch.items()}
    for slot, c in enumerate(uniq):
        sel = np.where(client_ids == c)[0][:batch_size]
        out_ids[slot] = c
        masks[slot, :len(sel)] = True
        for k, v in batch.items():
            gathered[k][slot, :len(sel)] = v[sel]
    return out_ids, gathered, masks


class FedOptimizer:
    """LR owner + reference-API shims (.step/.zero_grad/.get_lr,
    ``param_groups`` for schedulers that poke ``param_groups[0]['lr']``)."""

    def __init__(self, cfg: FedConfig, lr: float = 1.0):
        self.cfg = cfg
        self.param_groups = [{"lr": lr}]
        self._model: Optional[FedModel] = None

    def get_lr(self) -> float:
        return float(self.param_groups[0]["lr"])

    def set_lr(self, lr: float) -> None:
        self.param_groups[0]["lr"] = lr

    def step(self) -> None:  # server update already applied in model(batch)
        pass

    def zero_grad(self) -> None:
        pass


class FedModel:
    """Callable federated model over a FedRuntime (reference
    fed_aggregator.py:54-381)."""

    def __init__(self, module, params, loss_fn_train: Callable,
                 cfg: FedConfig, loss_fn_val: Optional[Callable] = None,
                 num_clients: Optional[int] = None, mesh=None):
        self.module = module
        self.runtime = FedRuntime(cfg, params, loss_fn_train, loss_fn_val,
                                  num_clients=num_clients, mesh=mesh)
        self.cfg = self.runtime.cfg
        self.state = self.runtime.init_state()
        self.training = True
        self._opt: Optional[FedOptimizer] = None

    # -------------------------------------------------------------- wiring

    def attach_optimizer(self, opt: FedOptimizer) -> FedOptimizer:
        self._opt = opt
        opt._model = self
        return opt

    def train(self, mode: bool = True) -> None:
        self.training = mode

    # ---------------------------------------------------------------- call

    def __call__(self, batch: Dict[str, np.ndarray]):
        client_ids = np.asarray(batch["client_id"])
        data = {k: np.asarray(v) for k, v in batch.items()
                if k != "client_id"}
        if self.training and (client_ids >= 0).all():
            return self._call_train(client_ids, data)
        return self._call_val(data)

    def _call_train(self, client_ids, data):
        lr = self._opt.get_lr() if self._opt is not None else 1.0
        bs = self.runtime.batch_size
        ids, gathered, masks = split_by_client(
            client_ids, data, self.cfg.num_workers, bs)
        gathered = {k: jnp.asarray(v) for k, v in gathered.items()}
        self.state, metrics = self.runtime.round(
            self.state, ids, gathered, jnp.asarray(masks), lr)
        losses = np.asarray(metrics["results"][0])
        accs = np.asarray(metrics["results"][1])
        download = (np.asarray(metrics["download_bytes"])
                    if metrics["download_bytes"] is not None else
                    np.zeros(self.runtime.num_clients))
        upload = (np.asarray(metrics["upload_bytes"])
                  if metrics["upload_bytes"] is not None else
                  np.zeros(self.runtime.num_clients))
        return losses, accs, download, upload

    def _call_val(self, data):
        # device-residency discipline (same as cv_train.run_validation):
        # per-chunk sums ACCUMULATE ON DEVICE and the host fetches once at
        # the end — a fetch inside the loop costs a full host<->device
        # round-trip per chunk on the high-latency tunnel runtime
        n = len(next(iter(data.values())))
        vb = self.cfg.valid_batch_size
        acc_sums = None
        for start in range(0, n, vb):
            idx = np.arange(start, min(start + vb, n))
            pad = vb - len(idx)
            chunk = {k: np.concatenate(
                [v[idx], np.zeros((pad,) + v.shape[1:], v.dtype)])
                for k, v in data.items()}
            mask = np.concatenate([np.ones(len(idx)), np.zeros(pad)])
            results, n_valid = self.runtime.val(
                self.state, {k: jnp.asarray(v) for k, v in chunk.items()},
                jnp.asarray(mask))
            contrib = jnp.stack([results[0] * n_valid,
                                 results[1] * n_valid, n_valid])
            acc_sums = contrib if acc_sums is None else acc_sums + contrib
        sums = (np.asarray(acc_sums) if acc_sums is not None
                else np.zeros(3))
        total = max(float(sums[2]), 1.0)
        return (np.array([float(sums[0]) / total]),
                np.array([float(sums[1]) / total]))

    # ------------------------------------------------------------ teardown

    def finalize(self) -> None:  # reference joins worker procs; no-op here
        pass

    def zero_grad(self) -> None:
        pass

    def get_params(self):
        """Materialized parameter pytree (reference state_dict trick,
        fed_aggregator.py:372-376)."""
        return self.runtime.get_params(self.state)

    def save_pretrained(self, path: str) -> None:
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 ps_weights=np.asarray(self.runtime.flat_weights(self.state)))
