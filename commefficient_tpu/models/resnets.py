"""torchvision-style ResNet family with a LayerNorm option.

Parity target: reference CommEfficient/models/resnets.py:133-370, whose two
deliberate modifications from stock torchvision are (a) the stem conv takes
**1 input channel** (EMNIST, resnets.py:155) and (b) every norm site can be
``nn.LayerNorm`` with explicit spatial shapes instead of BatchNorm
(resnets.py:87-97, 157-160, 199-204) — BN-free variants matter because
BatchNorm breaks under tiny non-iid federated client batches. Our
``SpatialLayerNorm`` infers the spatial shape from the traced activation, so
no hand-threaded ``hw`` bookkeeping is needed.

Constructors mirror the reference's exported names
(``resnet18`` … ``wide_resnet101_2``, models/__init__.py:1-7) plus
``ResNet101LN`` (models/resnet101ln.py:7-13: resnet101 + LayerNorm,
62 classes for FEMNIST).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
from flax import linen as nn

from commefficient_tpu.models.layers import (
    conv1x1,
    conv3x3,
    global_avg_pool,
    make_norm,
    max_pool,
)


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    norm: str = "batch"
    groups: int = 1
    base_width: int = 64
    expansion: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        Norm = make_norm(self.norm)
        y = conv3x3(self.features, stride=self.stride)(x)
        y = nn.relu(Norm()(y))
        y = conv3x3(self.features)(y)
        y = Norm()(y)
        if self.stride != 1 or x.shape[-1] != self.features:
            x = Norm()(conv1x1(self.features, stride=self.stride,
                               name="downsample_conv")(x))
        return nn.relu(y + x)


class Bottleneck(nn.Module):
    features: int           # "planes"; output width is features * 4
    stride: int = 1
    norm: str = "batch"
    groups: int = 1
    base_width: int = 64
    expansion: int = 4

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        Norm = make_norm(self.norm)
        width = int(self.features * (self.base_width / 64.0)) * self.groups
        out_ch = self.features * self.expansion
        y = nn.relu(Norm()(conv1x1(width)(x)))
        y = nn.relu(Norm()(conv3x3(width, stride=self.stride,
                                   groups=self.groups)(y)))
        y = Norm()(conv1x1(out_ch)(y))
        if self.stride != 1 or x.shape[-1] != out_ch:
            x = Norm()(conv1x1(out_ch, stride=self.stride,
                               name="downsample_conv")(x))
        return nn.relu(y + x)


class ResNet(nn.Module):
    block: Callable[..., nn.Module]
    layers: Sequence[int]
    num_classes: int = 1000
    norm: str = "batch"
    groups: int = 1
    width_per_group: int = 64
    initial_channels: int = 1  # reference hardcodes 1 (EMNIST), resnets.py:155

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        Norm = make_norm(self.norm)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                    name="stem")(x)
        x = nn.relu(Norm()(x))
        x = max_pool(x, 3, stride=2, padding=((1, 1), (1, 1)))
        for stage, (planes, n) in enumerate(zip((64, 128, 256, 512),
                                                self.layers)):
            for i in range(n):
                x = self.block(planes, stride=(2 if stage > 0 and i == 0
                                               else 1),
                               norm=self.norm, groups=self.groups,
                               base_width=self.width_per_group,
                               name=f"stage{stage}_block{i}")(x)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, name="fc")(x)


def _make(block, layers, **fixed):
    def ctor(num_classes: int = 1000, norm: str = "batch",
             initial_channels: int = 1, **kw):
        return ResNet(block=block, layers=layers, num_classes=num_classes,
                      norm=norm, initial_channels=initial_channels,
                      **{**fixed, **kw})
    return ctor


resnet18 = _make(BasicBlock, (2, 2, 2, 2))
resnet34 = _make(BasicBlock, (3, 4, 6, 3))
resnet50 = _make(Bottleneck, (3, 4, 6, 3))
resnet101 = _make(Bottleneck, (3, 4, 23, 3))
resnet152 = _make(Bottleneck, (3, 8, 36, 3))
resnext50_32x4d = _make(Bottleneck, (3, 4, 6, 3), groups=32, width_per_group=4)
resnext101_32x8d = _make(Bottleneck, (3, 4, 23, 3), groups=32,
                         width_per_group=8)
wide_resnet50_2 = _make(Bottleneck, (3, 4, 6, 3), width_per_group=128)
wide_resnet101_2 = _make(Bottleneck, (3, 4, 23, 3), width_per_group=128)


def ResNet101LN(num_classes: int = 62, **kw):
    """resnet101 with LayerNorm everywhere, 62 classes (FEMNIST) —
    reference models/resnet101ln.py:7-13."""
    return resnet101(num_classes=num_classes, norm="layer", **kw)
