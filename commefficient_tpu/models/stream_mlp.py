"""StreamMLP: the reference scan-structured model for the fused sketch
encode's ``streaming_grad`` hook (core/client.py make_forward_grad /
make_fused_grad).

Why this exists
---------------
The generic fused-encode path differentiates the loss w.r.t. the
parameter PYTREE and streams each leaf cotangent into the Count Sketch
table (``encode_grad_tree``) — the dense ``(d,)`` gradient sum never
exists, but the backward still PRODUCES every leaf cotangent before XLA
schedules the first encode, so roughly the whole ``d``-float tree sits
live at the backward's end (~1.9x ``d*4`` temp measured on the CPU
ledger, vs the theoretical one-layer-at-a-time interleave). The only
way below ``d*4`` is a backward that *consumes each layer's gradient as
it is produced* — which means the model must own its backward.

``StreamMLP`` is that model, the miniature of GPT-2's scan-over-blocks
structure: ``L`` identical dense+relu blocks whose parameters are one
stacked ``(L, H, H)`` leaf. ``make_stream_mlp_loss`` builds the
standard ``loss_fn(params, batch, mask) -> (loss, (acc,))`` closure AND
attaches the ``streaming_grad`` implementation:

- forward keeps the per-layer inputs (``(L, B, H)`` — activations, not
  parameters: tiny) and reads each layer's weights ON DEMAND with a
  ``dynamic_slice`` of ``params_vec`` inside the layer scan — the
  stacked ``(L, H, H)`` leaf is never materialized, so the weights
  stay in ARGUMENT space (a whole-tree ``unravel`` would put a second
  d-sized copy in temp and single-handedly blow the ``< d*4`` gate);
- the backward walks layers LAST to FIRST (the natural cotangent
  order), computes one layer's ``(H, H)`` weight gradient, encodes it
  into the carry table at its static ravel offset, and — the part no
  generic autodiff pipeline can do — couples the next layer's
  activation cotangent to the updated table with a
  ``lax.optimization_barrier``, so the schedule PROVABLY holds at most
  one layer's parameter gradient live at a time. The barrier alone is
  not enough: the layer's weight slice and the encode's ±1 sign
  streams are pure index arithmetic, which the scheduler would
  otherwise compute UP FRONT for every layer at once (measured: 24
  concurrent sign tensors — r·L ranges — put the "streaming" backward
  right back at 4x d*4). Both are therefore keyed on an opaque zero
  derived from the barrier-chained cotangent (``loop_token_zero``), so
  layer l's slices and signs cannot exist before layer l+1's encode
  completed. Peak temp is ``O(d/L + B·H·L + r·c)`` — under ``d*4``
  whenever the model has more than a couple of blocks (the
  dryrun_multichip fused-encode gate asserts exactly this on the split
  round's client executable).

The manual VJP is pinned against ``jax.grad`` by
tests/test_fused_encode.py (same cotangents to fp tolerance), and the
streamed table against encode(dense gradient) by sketch linearity.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from commefficient_tpu.ops.sketch import loop_token_zero


def init_stream_mlp(key: jax.Array, d_in: int, hidden: int, n_layers: int,
                    n_classes: int, scale: float = 0.3) -> Dict[str, Any]:
    """Parameter pytree: ``inp`` (d_in, H), ``blocks_w`` (L, H, H),
    ``blocks_b`` (L, H), ``out`` (H, C). Plain dict — ``ravel_params``
    flattens leaves in sorted-key order (blocks_b, blocks_w, inp, out),
    which is the layout ``streaming_grad``'s static offsets assume."""
    k1, k2, k3 = jax.random.split(key, 3)
    h = hidden
    return {
        "blocks_b": jnp.zeros((n_layers, h), jnp.float32),
        "blocks_w": scale * jax.random.normal(k1, (n_layers, h, h),
                                              jnp.float32) / jnp.sqrt(h),
        "inp": scale * jax.random.normal(k2, (d_in, h),
                                         jnp.float32) / jnp.sqrt(d_in),
        "out": scale * jax.random.normal(k3, (h, n_classes),
                                         jnp.float32) / jnp.sqrt(h),
    }


def _forward(params: Dict[str, Any], x: jax.Array
             ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (logits, hs, h_final) with ``hs[l]`` the INPUT of block l
    (hs: (L, B, H) — the backward's saved activations) and ``h_final``
    the last block's output (the output head's input)."""

    h = x @ params["inp"]

    def body(h, wb):
        w, b = wb
        return jax.nn.relu(h @ w + b), h

    h, hs = lax.scan(body, h, (params["blocks_w"], params["blocks_b"]))
    return h @ params["out"], hs, h


def make_stream_mlp_loss(params_template: Dict[str, Any]):
    """Build the driver-contract loss closure for a StreamMLP parameter
    tree and attach its ``streaming_grad``.

    ``loss_fn(params, batch, mask) -> (masked-mean NLL, (accuracy,))``
    with ``batch = {"x": (B, d_in), "target": (B,)}``; and

    ``loss_fn.streaming_grad(params_vec, batch, mask, cs, table,
    scale=None) -> (table', loss, metrics)``

    where ``table' == table + cs.encode(scale * dense_grad)`` up to fp
    order and ``dense_grad`` is exactly ``jax.grad`` of the same loss in
    ravel layout (test-pinned). ``scale`` folds into the logits
    cotangent — everything downstream is linear in it."""
    L, H = params_template["blocks_w"].shape[:2]
    d_in = params_template["inp"].shape[0]
    C = params_template["out"].shape[1]
    # static ravel offsets of the sorted-key leaf layout
    off_b = 0
    off_w = off_b + L * H
    off_inp = off_w + L * H * H
    off_out = off_inp + d_in * H

    def _loss_from_logits(logits, target, mask):
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        lp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(lp, target[:, None], axis=1)[:, 0]
        loss = (nll * m).sum() / denom
        acc = ((logits.argmax(axis=1) == target) * m).sum() / denom
        return loss, acc

    def loss_fn(params, batch, mask):
        logits, _, _ = _forward(params, batch["x"])
        loss, acc = _loss_from_logits(logits, batch["target"], mask)
        return loss, (acc,)

    def _slice(params_vec, start, n, zi=None):
        """One leaf range of ``params_vec``, read in place. ``zi`` is an
        opaque zero offset (see loop_token_zero) serializing the slice
        behind the backward's barrier chain — without it every layer's
        weight slice is loop-invariant index arithmetic the scheduler
        happily materializes up front, all L at once."""
        if zi is not None:
            start = start + zi
        return lax.dynamic_slice(params_vec, (start,), (n,))

    def streaming_grad(params_vec, batch, mask, cs, table, scale=None):
        x, target = batch["x"], batch["target"]
        # forward: layer weights are dynamic-sliced from params_vec one
        # layer at a time inside the scan — numerically the exact dots
        # of loss_fn's pytree forward (slice+reshape changes no values),
        # but the (L, H, H) stacked leaf never exists as a buffer
        h0 = x @ _slice(params_vec, off_inp, d_in * H).reshape(d_in, H)

        def fwd_body(h, l):
            w = _slice(params_vec, off_w + l * H * H, H * H).reshape(H, H)
            b = _slice(params_vec, off_b + l * H, H)
            return jax.nn.relu(h @ w + b), h

        h_last, hs = lax.scan(fwd_body, h0,
                              jnp.arange(L, dtype=jnp.int32))
        w_out = _slice(params_vec, off_out, H * C).reshape(H, C)
        logits = h_last @ w_out
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        loss, acc = _loss_from_logits(logits, target, mask)
        # d(loss)/d(logits) of the masked-mean NLL; the client's datum
        # weighting (``scale``) folds in here — every parameter
        # cotangent below is linear in it
        p = jax.nn.softmax(logits)
        dlogits = (p - jax.nn.one_hot(target, C)) * (m / denom)[:, None]
        if scale is not None:
            dlogits = dlogits * scale
        # output head: its cotangent is produced first and dies at its
        # encode — exactly the discipline the generic tree path cannot
        # force on XLA's scheduler
        table = cs.encode_accum(table, (h_last.T @ dlogits).reshape(-1),
                                off_out, token=loss)
        dh = dlogits @ w_out.T
        for l in range(L - 1, -1, -1):
            # the token is re-derived from the BARRIER-CHAINED cotangent
            # each layer: this layer's weight slice AND its encodes'
            # sign streams now depend on the previous layer's encode
            # having completed, not just on the loss
            tok = dh[0, 0]
            zi = loop_token_zero(tok).astype(jnp.int32)
            w = _slice(params_vec, off_w + l * H * H, H * H,
                       zi).reshape(H, H)
            b = _slice(params_vec, off_b + l * H, H, zi)
            z = hs[l] @ w + b
            dz = dh * (z > 0)
            table = cs.encode_accum(table, (hs[l].T @ dz).reshape(-1),
                                    off_w + l * H * H, token=tok)
            table = cs.encode_accum(table, dz.sum(axis=0),
                                    off_b + l * H, token=tok)
            dh = dz @ w.T
            # the coupling is the whole trick: the NEXT layer's backward
            # must depend on THIS layer's encode having completed, so
            # the scheduler cannot run the full backward first and park
            # every layer's (H, H) cotangent in HBM — at most one is
            # live at any point (the dryrun gate's temp < d*4 proof).
            # An optimization_barrier is NOT enough: the CPU pipeline
            # expands barriers away before scheduling (76 in the
            # unoptimized module, 0 after optimization — measured), so
            # the dependency must be DATA: an opaque zero derived from
            # the updated table (un-foldable for the same fp reasons as
            # loop_token_zero: x*0 is NaN for nonfinite x, so the
            # simplifier cannot elide it; the NaN squash keeps a
            # diverging table from poisoning the cotangent) folds into
            # dh, and the barrier stays for backends that do honor it
            tz = table[0, 0] * 0.0
            dh = dh + jnp.where(jnp.isnan(tz), 0.0, tz)
            dh, table = lax.optimization_barrier((dh, table))
        table = cs.encode_accum(table, (x.T @ dh).reshape(-1), off_inp,
                                token=dh[0, 0])
        return table, loss, (acc,)

    loss_fn.streaming_grad = streaming_grad
    return loss_fn
