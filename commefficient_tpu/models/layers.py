"""Shared building blocks for the model zoo.

TPU-first conventions that differ from the reference's torch models
(CommEfficient/models/*):

- **NHWC layout.** Flax/XLA convolutions are fastest channel-last on TPU;
  the reference's NCHW is a CUDA/cuDNN artifact.
- **Stateless BatchNorm.** The reference's ``do_batchnorm`` path keeps
  running statistics (models/resnet9.py:17-29) which are mutable state a
  functional, vmapped-per-client federated step cannot thread (and which are
  exactly what breaks under tiny non-iid client batches — the reason the
  reference grew its Fixup/LayerNorm variants, models/resnets.py:87-97).
  ``BatchStatNorm`` normalizes with the *current* batch statistics in both
  train and eval, which under per-client vmap gives each simulated client
  its own statistics — the federated-correct semantics.
- **Scalar Fixup params** (scale/bias) are rank-0 arrays, matching the
  reference's ``nn.Parameter(torch.zeros(1))`` (models/fixup_resnet18.py:8-22)
  in effect.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def conv3x3(features: int, stride: int = 1, groups: int = 1,
            dilation: int = 1, name: Optional[str] = None) -> nn.Conv:
    return nn.Conv(features, (3, 3), strides=(stride, stride),
                   padding=dilation, feature_group_count=groups,
                   kernel_dilation=(dilation, dilation), use_bias=False,
                   name=name)


def conv1x1(features: int, stride: int = 1,
            name: Optional[str] = None) -> nn.Conv:
    return nn.Conv(features, (1, 1), strides=(stride, stride),
                   padding="VALID", use_bias=False, name=name)


def max_pool(x: jax.Array, window: int, stride: Optional[int] = None,
             padding: Any = "VALID") -> jax.Array:
    stride = stride if stride is not None else window
    return nn.max_pool(x, (window, window), (stride, stride), padding)


def global_avg_pool(x: jax.Array) -> jax.Array:
    return x.mean(axis=(1, 2))


def global_max_pool(x: jax.Array) -> jax.Array:
    return x.max(axis=(1, 2))


class BatchStatNorm(nn.Module):
    """BatchNorm without running statistics (always batch stats).

    Learned per-channel scale/bias; normalization over (N, H, W). See module
    docstring for why this replaces the reference's stateful BatchNorm2d.

    EVAL CAVEAT (measured, round 4): because eval batches normalize by
    their OWN statistics, the stat noise of a small eval batch compounds
    with depth — a 50-layer torchvision resnet50 evaluated with 8-image
    batches returns chance-level accuracy on data it fits to 94% train
    accuracy, while the same checkpoint evaluated with 256-image batches
    tracks train accuracy. Shallow stacks (ResNet-9) are robust at batch
    8. Use ``--valid_batch_size`` >= 64 with deep batch-normed models
    (cv_train warns); or pick ``norm='layer'`` for batch-size-free eval.
    """

    epsilon: float = 1e-5
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,))
        bias = self.param("bias", self.bias_init, (c,))
        mean = x.mean(axis=(0, 1, 2), keepdims=True)
        var = x.var(axis=(0, 1, 2), keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * scale + bias


class SpatialLayerNorm(nn.Module):
    """LayerNorm over the full (H, W, C) feature map of each example —
    the semantics of the reference's ``nn.LayerNorm((C, hw, hw))`` with
    explicit static spatial shapes (models/resnets.py:87-97). Shape-agnostic
    here because normalized axes are all non-batch axes."""

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        shape = x.shape[1:]
        scale = self.param("scale", nn.initializers.ones, shape)
        bias = self.param("bias", nn.initializers.zeros, shape)
        mean = x.mean(axis=(1, 2, 3), keepdims=True)
        var = x.var(axis=(1, 2, 3), keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * scale + bias


class Scalar(nn.Module):
    """A single learned scalar, used multiplicatively or additively by the
    Fixup blocks."""

    init_value: float = 0.0

    @nn.compact
    def __call__(self) -> jax.Array:
        return self.param(
            "value", lambda _key: jnp.asarray(self.init_value, jnp.float32))


def make_norm(norm: str) -> Callable[..., nn.Module]:
    """Norm factory: 'batch' -> BatchStatNorm, 'layer' -> SpatialLayerNorm,
    'none' -> identity."""
    if norm == "batch":
        return BatchStatNorm
    if norm == "layer":
        return SpatialLayerNorm
    if norm == "none":
        return lambda **kw: (lambda x: x)  # type: ignore[return-value]
    raise ValueError(f"unknown norm {norm!r}")


def fixup_conv_init(num_layers: int) -> Callable:
    """He-init scaled by L^(-1/2) for the first conv of a Fixup block
    (reference models/fixup_resnet18.py:88-94)."""
    he = nn.initializers.variance_scaling(2.0, "fan_out", "normal")

    def init(key, shape, dtype=jnp.float32):
        return he(key, shape, dtype) * num_layers ** (-0.5)

    return init
