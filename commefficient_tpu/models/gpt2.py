"""GPT-2 with LM + multiple-choice heads, Flax from scratch.

Parity target: the reference's external ``GPT2DoubleHeadsModel`` from
``pytorch_transformers`` (gpt2_train.py:4-6, 262-285): token + learned
position + token-type embeddings, pre-LN causal transformer, LM head tied to
the token embedding, and a multiple-choice head that scores each candidate
from the hidden state at its ``mc_token_id`` (the last token). The reference
resizes embeddings after adding 5 special tokens
(``add_special_tokens_``, gpt2_train.py:101-112) — here ``num_added_tokens``
sizes the table up front and ``load_hf_weights`` pads the pretrained rows.

TPU-native choices: bfloat16 activations with fp32 LayerNorm/softmax
accumulation; attention is pluggable (``attn_impl``) so the same module runs
dense single-chip attention or ring attention over a ``seq`` mesh axis
(parallel/ring.py) for long-context — new scope beyond the reference, which
has no sequence parallelism (SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

NUM_SPECIAL_TOKENS = 5  # <bos> <eos> <speaker1> <speaker2> <pad>


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    num_added_tokens: int = NUM_SPECIAL_TOKENS
    layer_norm_eps: float = 1e-5
    compute_dtype: Any = jnp.bfloat16
    # rematerialize each block on the backward pass (jax.checkpoint):
    # trades recompute FLOPs for HBM — the standard long-context memory move
    remat: bool = False
    # selective-remat policy name (jax.checkpoint_policies attribute, e.g.
    # "dots_with_no_batch_dims_saveable"): save matmul outputs, recompute
    # the cheap elementwise rest — spends a little of the memory remat
    # freed to skip most of the recompute FLOPs. Empty = full remat.
    remat_policy: str = ""
    # lax.scan over the layer stack (stacked block params) instead of
    # unrolling n_layer blocks into the graph: XLA compiles ONE block body,
    # cutting compile time ~n_layer-fold for deep models — essential when
    # the whole federated round (vmap over clients x grad x microbatch scan)
    # wraps the model
    scan_layers: bool = True

    @property
    def total_vocab(self) -> int:
        return self.vocab_size + self.num_added_tokens

    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        """A tiny config for tests/smoke (not a reference size)."""
        base = dict(vocab_size=256, n_positions=128, n_embd=64, n_layer=2,
                    n_head=4)
        base.update(kw)
        return cls(**base)


def gpt2_model_flops(gcfg: "GPT2Config", tokens: int, S: int) -> float:
    """Analytic fwd+bwd model FLOPs for ``tokens`` tokens of this config
    at sequence length S (2 FLOPs per MAC; backward = 2x forward):

    - block matmuls: qkv 3E^2 + attn proj E^2 + mlp 8E^2 = 12E^2 MACs
      per token per layer,
    - attention scores+values: 2*S*E MACs per token per layer (causal
      masking not discounted — consistent with common MFU practice),
    - tied LM head: E*V MACs per token.

    This is the MFU numerator for the scanned GPT-2 round: XLA's
    ``cost_analysis`` counts each ``lax.scan`` body once (no trip-count
    multiply), under-reporting the microbatch/layer-scanned round ~10x —
    so both ``bench_gpt2.py`` and the ``gpt2_train`` driver feed this
    closed form to ``telemetry/utilization.py`` instead.
    """
    E, L, V = gcfg.n_embd, gcfg.n_layer, gcfg.total_vocab
    fwd_per_tok = 2 * (12 * E * E * L + 2 * S * E * L + E * V)
    return 3.0 * fwd_per_tok * tokens


def dense_causal_attention(q, k, v, dropout_rng=None):
    """Plain causal attention: q,k,v (..., S, H, D) -> (..., S, H, D).
    fp32 softmax accumulation regardless of input dtype."""
    S = q.shape[-3]
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32)
    logits = logits * scale
    mask = jnp.tril(jnp.ones((S, S), bool))
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, v)


def flash_causal_attention(q, k, v, dropout_rng=None, _warn_fallback=False):
    """Fused-softmax causal attention via the TPU Pallas flash kernel
    (jax.experimental.pallas.ops.tpu.flash_attention): never materializes
    the (H, S, S) logits tensor, so attention activation memory drops from
    O(S^2) to O(S) — which is what lets the flagship GPT-2 round turn
    block remat OFF (the logits tensors were the microbatch-8 memory
    wall) and skip the ~33% backward recompute. Falls back to the dense
    path off-TPU and for sequence lengths the kernel's lane tiling cannot
    cover (S % 128 != 0); an EXPLICIT --attn_impl flash request warns on
    that fallback (``_warn_fallback``, set by resolve_attn) so users don't
    attribute dense-path memory/speed to flash (ADVICE r4)."""
    S, D = q.shape[-3], q.shape[-1]
    if jax.default_backend() != "tpu" or S % 128:
        if _warn_fallback:
            import warnings
            warnings.warn(
                "attn_impl='flash' was requested but the kernel is "
                f"ineligible here (backend={jax.default_backend()!r}, "
                f"S={S}{'' if S % 128 == 0 else ' % 128 != 0'}): running "
                "DENSE attention instead — memory/speed will be the dense "
                "path's (e.g. the PERSONA default max_seq_len=280 is "
                "unaligned; pick a multiple of 128)", stacklevel=2)
        return dense_causal_attention(q, k, v)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention)
    lead = q.shape[:-3]
    H = q.shape[-2]

    def to4(t):  # (..., S, H, D) -> (B, H, S, D)
        return jnp.moveaxis(t.reshape((-1,) + t.shape[-3:]), -2, 1)

    # the kernel requires its block sizes to DIVIDE S; S % 128 == 0 is
    # guaranteed above, so the largest dividing power-of-two block <= 512
    # always exists (512 itself need not divide e.g. S=640)
    blk = max(b for b in (512, 256, 128) if S % b == 0)
    sizes = BlockSizes(block_q=blk, block_k_major=blk, block_k=blk,
                       block_b=1, block_q_major_dkv=blk,
                       block_k_major_dkv=blk, block_k_dkv=blk,
                       block_q_dkv=blk, block_k_major_dq=blk,
                       block_k_dq=blk, block_q_dq=blk)
    out = flash_attention(to4(q), to4(k), to4(v), causal=True,
                          sm_scale=1.0 / math.sqrt(D), block_sizes=sizes)
    return jnp.moveaxis(out, 1, -2).reshape(lead + (S, H, D))


def auto_causal_attention(q, k, v, dropout_rng=None):
    """Measured-crossover policy (scripts/bench_longctx.py, one v5e):
    dense wins below S=1024 (at S=256 the flash grid overhead exceeds
    what fusing a small softmax saves — 485 vs 410 ms on the flagship
    round); flash wins from S=1024 up and holds ~30% MFU flat where the
    dense path collapses (S=4096: 3.05x — 49.7k vs 16.3k tok/s). The
    sequence length is static at trace time, so this dispatch costs
    nothing."""
    if q.shape[-3] >= 1024:
        return flash_causal_attention(q, k, v)
    return dense_causal_attention(q, k, v)


ATTN_IMPLS = {"dense": dense_causal_attention,
              # explicit flash requests warn when the eligibility check
              # falls back to dense (auto's fallbacks stay silent: its
              # dense dispatch below S=1024 is the measured-crossover
              # POLICY, not a degradation)
              "flash": functools.partial(flash_causal_attention,
                                         _warn_fallback=True),
              "auto": auto_causal_attention}


def resolve_attn(name: str) -> Callable:
    """Config-string -> attention callable (config.py --attn_impl)."""
    try:
        return ATTN_IMPLS[name]
    except KeyError:
        raise ValueError(f"unknown attn_impl {name!r}: "
                         f"want one of {sorted(ATTN_IMPLS)}") from None


class Block(nn.Module):
    cfg: GPT2Config
    attn_impl: Callable = dense_causal_attention

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        H, D = cfg.n_head, cfg.n_embd // cfg.n_head
        dt = cfg.compute_dtype

        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_1")(x).astype(dt)
        qkv = nn.Dense(3 * cfg.n_embd, dtype=dt, name="c_attn")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(t.shape[:-1] + (H, D))
        a = self.attn_impl(split(q), split(k), split(v))
        a = a.reshape(a.shape[:-2] + (cfg.n_embd,))
        x = x + nn.Dense(cfg.n_embd, dtype=dt, name="c_proj")(a)

        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_2")(x).astype(dt)
        h = nn.Dense(4 * cfg.n_embd, dtype=dt, name="c_fc")(h)
        h = nn.gelu(h, approximate=True)
        x = x + nn.Dense(cfg.n_embd, dtype=dt, name="mlp_proj")(h)
        return x


class _ScanBody(nn.Module):
    """carry/out adapter so ``nn.scan`` can drive a plain x->x Block."""

    block_cls: Callable
    cfg: GPT2Config
    attn_impl: Callable

    @nn.compact
    def __call__(self, x, _):
        return self.block_cls(self.cfg, self.attn_impl, name="block")(x), None


class GPT2Backbone(nn.Module):
    """``seq_axis``/``seq_shards``: when set, the module expects to run
    INSIDE a shard_map whose mesh has that axis, with every (..., S, ...)
    input already holding only the local S/seq_shards token shard: position
    ids become global (offset by the shard index), and attention runs as
    ring attention over the axis (parallel/ring.py) — the long-context
    configuration the reference lacks entirely (SURVEY.md §5)."""

    cfg: GPT2Config
    attn_impl: Callable = dense_causal_attention
    seq_axis: Optional[str] = None
    seq_shards: int = 1

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None):
        cfg = self.cfg
        S = input_ids.shape[-1]
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.total_vocab, cfg.n_embd))
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd))
        if position_ids is None:
            if self.seq_axis is not None:
                position_ids = (lax.axis_index(self.seq_axis) * S
                                + jnp.arange(S))
            else:
                position_ids = jnp.arange(S)
        x = wte[input_ids] + wpe[position_ids]
        if token_type_ids is not None:
            x = x + wte[token_type_ids]
        x = x.astype(cfg.compute_dtype)
        attn = self.attn_impl
        if self.seq_axis is not None:
            from commefficient_tpu.parallel.ring import ring_attention_inner
            attn = functools.partial(ring_attention_inner,
                                     axis_name=self.seq_axis,
                                     num_shards=self.seq_shards)
        if cfg.remat and cfg.remat_policy:
            if not hasattr(jax.checkpoint_policies, cfg.remat_policy):
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r}: must be "
                    "an attribute of jax.checkpoint_policies (e.g. "
                    "dots_with_no_batch_dims_saveable)")
            block_cls = nn.remat(
                Block,
                policy=getattr(jax.checkpoint_policies, cfg.remat_policy))
        elif cfg.remat:
            block_cls = nn.remat(Block)
        else:
            block_cls = Block
        if cfg.scan_layers:
            scanned = nn.scan(
                _ScanBody, variable_axes={"params": 0},
                split_rngs={"params": True}, length=cfg.n_layer,
                metadata_params={nn.meta.PARTITION_NAME: None})
            x, _ = scanned(block_cls, cfg, attn, name="h")(x, None)
        else:
            for i in range(cfg.n_layer):
                x = block_cls(cfg, attn, name=f"h{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_f")(x)
        return x, wte


class GPT2DoubleHeads(nn.Module):
    """LM + MC heads over the backbone.

    ``input_ids``/``token_type_ids``: (..., S); ``mc_token_ids``: (...,) index
    of the scoring token per sequence. Returns (lm_logits fp32 (..., S, V),
    mc_logits fp32 (...,)).
    """

    cfg: GPT2Config
    attn_impl: Callable = dense_causal_attention
    seq_axis: Optional[str] = None
    seq_shards: int = 1

    def __call__(self, input_ids, mc_token_ids, token_type_ids=None):
        hidden, wte, mc_logits = self.hidden_and_mc(input_ids, mc_token_ids,
                                                    token_type_ids)
        lm_logits = (hidden @ wte.T.astype(hidden.dtype)).astype(jnp.float32)
        return lm_logits, mc_logits

    @nn.compact
    def hidden_and_mc(self, input_ids, mc_token_ids, token_type_ids=None):
        """Backbone output WITHOUT the (tokens, vocab) LM projection:
        (hidden, wte, mc_logits). The chunked-CE loss path
        (losses._chunked_lm_nll) projects and softmaxes vocab logits
        chunk-by-chunk instead — at microbatch 8 the full fp32 logits
        tensor alone is ~0.8 GB and (with its cotangent) is what capped
        the GPT-2 round's microbatch size."""
        hidden, wte = GPT2Backbone(self.cfg, self.attn_impl,
                                   seq_axis=self.seq_axis,
                                   seq_shards=self.seq_shards,
                                   name="transformer")(
            input_ids, token_type_ids)
        # mc_head is bias-free: a bias on a 1-unit head shifts every
        # candidate's logit equally, which the MC softmax is invariant to —
        # and bias-freeness lets the seq-sharded branch psum LOGIT
        # contributions (linear), so the kernel's gradient flows only from
        # the owning shard's tokens instead of duplicating across the axis
        mc_head = nn.Dense(1, use_bias=False, dtype=jnp.float32,
                           name="mc_head")
        if self.seq_axis is not None:
            # mc_token_ids are GLOBAL positions; exactly one seq shard owns
            # each and contributes; the psum replicates the logits
            S = hidden.shape[-2]
            local = mc_token_ids - lax.axis_index(self.seq_axis) * S
            owned = (local >= 0) & (local < S)
            li = jnp.clip(local, 0, S - 1)
            contrib = jnp.take_along_axis(
                hidden, li[..., None, None], axis=-2)[..., 0, :]
            contrib = jnp.where(owned[..., None], contrib, 0.0)
            mc_logits = lax.psum(mc_head(contrib)[..., 0], self.seq_axis)
        else:
            mc_hidden = jnp.take_along_axis(
                hidden, mc_token_ids[..., None, None], axis=-2)[..., 0, :]
            mc_logits = mc_head(mc_hidden)[..., 0]
        return hidden, wte, mc_logits


class GPT2LMHead(nn.Module):
    """Pure LM variant (no MC head) for generic language modeling."""

    cfg: GPT2Config
    attn_impl: Callable = dense_causal_attention

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        hidden, wte = GPT2Backbone(self.cfg, self.attn_impl,
                                   name="transformer")(
            input_ids, token_type_ids)
        return (hidden @ wte.T.astype(hidden.dtype)).astype(jnp.float32)


# HF GPT-2 uses Conv1D: weights already (in, out) — matches Dense
_HF_OF = {("c_attn", "kernel"): "attn.c_attn.weight",
          ("c_attn", "bias"): "attn.c_attn.bias",
          ("c_proj", "kernel"): "attn.c_proj.weight",
          ("c_proj", "bias"): "attn.c_proj.bias",
          ("c_fc", "kernel"): "mlp.c_fc.weight",
          ("c_fc", "bias"): "mlp.c_fc.bias",
          ("mlp_proj", "kernel"): "mlp.c_proj.weight",
          ("mlp_proj", "bias"): "mlp.c_proj.bias",
          ("ln_1", "scale"): "ln_1.weight",
          ("ln_1", "bias"): "ln_1.bias",
          ("ln_2", "scale"): "ln_2.weight",
          ("ln_2", "bias"): "ln_2.bias"}


def load_state_dict(params, cfg: GPT2Config, sd):
    """Fill a ``GPT2DoubleHeads``/``GPT2LMHead`` param pytree from an
    HF-GPT-2-layout ``name -> ndarray`` mapping (``wte.weight``,
    ``h.<i>.attn.c_attn.weight``, ..., as produced by
    ``GPT2Model.state_dict()``), padding the embedding table for the added
    special tokens with the mean embedding — the effect of the reference's
    post-``add_special_tokens_`` resize (gpt2_train.py:101-112, 262-285).

    Pure mapping, no I/O: missing keys raise ``KeyError`` and wrong shapes
    raise ``ValueError`` loudly (a key-mapping bug must never ship silently
    — VERDICT r4 missing #3). Handles both layer layouts: ``scan_layers``
    (one ``h/block`` subtree, layer axis stacked as each leaf's leading
    dim) and unrolled ``h<i>`` blocks. Fixture-tested end to end in
    tests/test_gpt2.py (synthesized checkpoint -> forward parity)."""
    import numpy as np

    def put(subtree, leaf, value):
        want = np.shape(subtree[leaf])
        if tuple(want) != np.shape(value):
            raise ValueError(
                f"HF weight shape {np.shape(value)} does not match target "
                f"leaf {leaf!r} shape {tuple(want)}")
        subtree[leaf] = jnp.asarray(value)

    p = jax.tree.map(lambda t: t, params)  # shallow copy
    tr = p["params"]["transformer"]
    wte = np.asarray(sd["wte.weight"])
    pad = np.tile(wte.mean(0, keepdims=True),
                  (cfg.total_vocab - wte.shape[0], 1))
    put(tr, "wte", np.concatenate([wte, pad], 0))
    put(tr, "wpe", np.asarray(sd["wpe.weight"])[: cfg.n_positions])

    if cfg.scan_layers:
        b = tr["h"]["block"]
        for (mod, leaf), hf_name in _HF_OF.items():
            put(b[mod], leaf, np.stack(
                [np.asarray(sd[f"h.{i}.{hf_name}"])
                 for i in range(cfg.n_layer)]))
    else:
        for i in range(cfg.n_layer):
            b = tr[f"h{i}"]
            for (mod, leaf), hf_name in _HF_OF.items():
                put(b[mod], leaf, np.asarray(sd[f"h.{i}.{hf_name}"]))
    put(tr["ln_f"], "scale", np.asarray(sd["ln_f.weight"]))
    put(tr["ln_f"], "bias", np.asarray(sd["ln_f.bias"]))
    return p


def load_hf_weights(params, cfg: GPT2Config, checkpoint: str = "gpt2"):
    """Thin I/O adapter over ``load_state_dict``: pull a local HuggingFace
    torch GPT-2 checkpoint's state dict and map it in. Returns the updated
    pytree, or None when transformers/the checkpoint is unavailable
    (zero-egress environments fall back to random init). Only the
    import/download can fail soft — mapping errors from ``load_state_dict``
    propagate loudly."""
    try:
        from transformers import GPT2Model  # noqa: WPS433
        hf = GPT2Model.from_pretrained(checkpoint, local_files_only=True)
    except Exception:
        return None
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    return load_state_dict(params, cfg, sd)
