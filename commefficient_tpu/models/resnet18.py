"""Self-contained CIFAR ResNet-18s: BN and Fixup variants.

Parity targets: reference CommEfficient/models/fixup_resnet18.py:66-216 —
3x3 prep conv to 64ch, four stages of two blocks each with widths
(64, 128, 256, 256) and strides (1, 2, 2, 2), a dual global avg+max pooled
head (concat -> 512 features) and a linear classifier. ``FixupResNet18`` uses
BN-free Fixup blocks (zero-init classifier, He/L^-0.5 conv1, zero conv2);
``ResNet18`` uses post-activation conv+BN blocks.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from commefficient_tpu.models.layers import (
    BatchStatNorm,
    conv1x1,
    conv3x3,
    global_avg_pool,
    global_max_pool,
)
from commefficient_tpu.models.resnet9 import FixupBasicBlock

STAGE_WIDTHS = (64, 128, 256, 256)
STAGE_STRIDES = (1, 2, 2, 2)


class BNBlock(nn.Module):
    """conv-bn-relu x2 with projection shortcut on shape change
    (reference ``PreActBlock`` as actually written, fixup_resnet18.py:139-166)."""

    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = conv3x3(self.features, stride=self.stride)(x)
        y = nn.relu(BatchStatNorm()(y))
        y = conv3x3(self.features)(y)
        y = nn.relu(BatchStatNorm()(y))
        if self.stride != 1 or x.shape[-1] != self.features:
            x = conv1x1(self.features, stride=self.stride)(x)
        return y + x


class _DualPoolHead(nn.Module):
    num_classes: int
    zero_init: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = jnp.concatenate([global_avg_pool(x), global_max_pool(x)], axis=-1)
        kernel_init = (nn.initializers.zeros if self.zero_init
                       else nn.initializers.lecun_normal())
        return nn.Dense(self.num_classes, kernel_init=kernel_init,
                        name="classifier")(x)


class ResNet18(nn.Module):
    num_classes: int = 10
    num_blocks: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.relu(conv3x3(64, name="prep")(x))
        for stage, (w, s, n) in enumerate(
                zip(STAGE_WIDTHS, STAGE_STRIDES, self.num_blocks)):
            for i in range(n):
                x = BNBlock(w, stride=s if i == 0 else 1,
                            name=f"stage{stage}_block{i}")(x)
        return _DualPoolHead(self.num_classes)(x)


class FixupResNet18(nn.Module):
    num_classes: int = 10
    num_blocks: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        depth = sum(self.num_blocks)
        x = nn.relu(conv3x3(64, name="prep")(x))
        for stage, (w, s, n) in enumerate(
                zip(STAGE_WIDTHS, STAGE_STRIDES, self.num_blocks)):
            for i in range(n):
                x = FixupBasicBlock(w, depth, stride=s if i == 0 else 1,
                                    name=f"stage{stage}_block{i}")(x)
        return _DualPoolHead(self.num_classes, zero_init=True)(x)
