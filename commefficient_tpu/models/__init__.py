"""Model zoo and name registry.

The reference selects models by reflected class name
(``getattr(models, args.model)``, cv_train.py:363; choices enumerated from
``dir(models)``, utils.py:114-118). Same surface here: every public model
name resolves through ``get_model``; ``MODEL_NAMES`` drives the CLI choices.
"""

from commefficient_tpu.models.resnet9 import ResNet9, FixupResNet9
from commefficient_tpu.models.resnet18 import ResNet18, FixupResNet18
from commefficient_tpu.models.fixup_resnet import (
    FixupResNet50,
    FixupResNetImageNet,
)
from commefficient_tpu.models.resnets import (
    ResNet101LN,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    resnext50_32x4d,
    resnext101_32x8d,
    wide_resnet50_2,
    wide_resnet101_2,
)

_REGISTRY = {
    "ResNet9": ResNet9,
    "FixupResNet9": FixupResNet9,
    "ResNet18": ResNet18,
    "FixupResNet18": FixupResNet18,
    "FixupResNet50": FixupResNet50,
    "ResNet101LN": ResNet101LN,
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
    "resnext50_32x4d": resnext50_32x4d,
    "resnext101_32x8d": resnext101_32x8d,
    "wide_resnet50_2": wide_resnet50_2,
    "wide_resnet101_2": wide_resnet101_2,
}

MODEL_NAMES = sorted(_REGISTRY)


def get_model(name: str):
    """Look up a model constructor by its reference-compatible name."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; choices: {MODEL_NAMES}") from None


__all__ = ["get_model", "MODEL_NAMES"] + list(_REGISTRY)
