"""ResNet-9 (cifar10_fast lineage) and its Fixup variant.

Behavioral parity targets:
- ``ResNet9``: reference CommEfficient/models/resnet9.py:132-148 (net at
  74-130) — prep 3x3 conv to 64ch, three ConvBN stages (128/256/512) with
  2x max-pool, residual pairs after stages 1 and 3, final 4x max-pool,
  bias-free linear head scaled by ``weight=0.125`` (the ``Mul`` classifier),
  optional batch norm via ``do_batchnorm``, and a finetune mode that swaps
  the head for ``new_num_classes`` and trains only head params
  (reference ``finetune_parameters``, models/resnet9.py:105-113).
- ``FixupResNet9``: reference models/fixup_resnet9.py:10-91 — the BN-free
  version built from Fixup-initialized layers with scalar scale/bias params.

TPU-native deviations: NHWC layout; stateless batch-stat normalization (see
models/layers.py docstring).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from commefficient_tpu.models.layers import (
    BatchStatNorm,
    Scalar,
    conv3x3,
    fixup_conv_init,
    max_pool,
)

DEFAULT_CHANNELS = {"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512}


class ConvBN(nn.Module):
    """3x3 conv (+ optional norm) + ReLU (+ optional 2x pool)."""

    features: int
    do_batchnorm: bool = False
    pool: int = 0  # 0 = no pool, else pool window

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = conv3x3(self.features)(x)
        if self.do_batchnorm:
            x = BatchStatNorm()(x)
        x = nn.relu(x)
        if self.pool:
            x = max_pool(x, self.pool)
        return x


class Residual(nn.Module):
    """x + relu(conv2(conv1(x))) with each conv a ConvBN
    (reference models/resnet9.py:61-68: ``x + F.relu(res2(res1(x)))``)."""

    features: int
    do_batchnorm: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = ConvBN(self.features, self.do_batchnorm)(x)
        y = ConvBN(self.features, self.do_batchnorm)(y)
        return x + nn.relu(y)


class ResNet9(nn.Module):
    do_batchnorm: bool = False
    num_classes: int = 10
    initial_channels: int = 3
    channels: Optional[Dict[str, int]] = None
    weight: float = 0.125
    pool: int = 2

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        ch = self.channels or DEFAULT_CHANNELS
        bn = self.do_batchnorm
        x = ConvBN(ch["prep"], bn)(x)
        x = ConvBN(ch["layer1"], bn, pool=self.pool)(x)
        x = Residual(ch["layer1"], bn)(x)
        x = ConvBN(ch["layer2"], bn, pool=self.pool)(x)
        x = ConvBN(ch["layer3"], bn, pool=self.pool)(x)
        x = Residual(ch["layer3"], bn)(x)
        # reference uses MaxPool2d(4) (models/resnet9.py:92), which on the
        # 4x4 CIFAR feature map IS global max pooling; the global form also
        # handles other input sizes (e.g. 28x28 EMNIST -> 3x3 here)
        x = x.max(axis=(1, 2))
        x = nn.Dense(self.num_classes, use_bias=False, name="head")(x)
        return x * self.weight


class FixupLayer(nn.Module):
    """conv(x + bias1a)*scale + bias1b, relu, pool, then ``num_blocks``
    Fixup basic blocks (reference models/fixup_resnet9.py:10-31)."""

    features: int
    num_blocks: int
    pool: int = 2
    num_layers: int = 2  # total fixup depth, for init scaling

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b1a = Scalar(0.0, name="bias1a")()
        b1b = Scalar(0.0, name="bias1b")()
        scale = Scalar(1.0, name="scale")()
        x = conv3x3(self.features)(x + b1a) * scale + b1b
        x = nn.relu(x)
        if self.pool:
            x = max_pool(x, self.pool)
        for i in range(self.num_blocks):
            x = FixupBasicBlock(self.features, self.num_layers,
                                name=f"block{i}")(x)
        return x


class FixupBasicBlock(nn.Module):
    """Two-conv Fixup residual block: conv1 He/L^-0.5 init, conv2 zero init,
    scalar biases around each conv and a scalar scale before the residual add
    (the arrangement of reference models/fixup_resnet18.py:24-64)."""

    features: int
    num_layers: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b1a = Scalar(0.0, name="bias1a")()
        b1b = Scalar(0.0, name="bias1b")()
        b2a = Scalar(0.0, name="bias2a")()
        b2b = Scalar(0.0, name="bias2b")()
        scale = Scalar(1.0, name="scale")()
        y = nn.Conv(self.features, (3, 3), strides=(self.stride, self.stride),
                    padding=1, use_bias=False,
                    kernel_init=fixup_conv_init(self.num_layers),
                    name="conv1")(x + b1a)
        y = nn.relu(y + b1b)
        y = nn.Conv(self.features, (3, 3), padding=1, use_bias=False,
                    kernel_init=nn.initializers.zeros, name="conv2")(y + b2a)
        y = y * scale + b2b
        if self.stride != 1 or x.shape[-1] != self.features:
            sc = nn.Conv(self.features, (1, 1),
                         strides=(self.stride, self.stride), padding="VALID",
                         use_bias=False, name="shortcut")(x)
        else:
            sc = x
        return nn.relu(y + sc)


class FixupResNet9(nn.Module):
    num_classes: int = 10
    channels: Optional[Dict[str, int]] = None
    pool: int = 2

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        ch = self.channels or DEFAULT_CHANNELS
        b1a = Scalar(0.0, name="bias1a")()
        b1b = Scalar(0.0, name="bias1b")()
        scale = Scalar(1.0, name="scale")()
        x = conv3x3(ch["prep"])(x + b1a) * scale + b1b
        x = nn.relu(x)
        x = FixupLayer(ch["layer1"], 1, pool=self.pool, name="layer1")(x)
        x = FixupLayer(ch["layer2"], 0, pool=self.pool, name="layer2")(x)
        x = FixupLayer(ch["layer3"], 1, pool=self.pool, name="layer3")(x)
        x = x.max(axis=(1, 2))  # global max pool (see ResNet9)
        b2 = Scalar(0.0, name="bias2")()
        x = nn.Dense(self.num_classes, name="head")(x + b2)
        return x
