"""Fixup ImageNet ResNets (BN-free bottleneck ResNet-50).

Parity target: reference CommEfficient/models/fixup_resnet.py:8-10, which
subclasses the external ``fixup`` package's ImageNet FixupResNet (Zhang et
al., "Fixup Initialization", ICLR 2019) with Bottleneck blocks [3,4,6,3].
That package is CUDA/torch; this is a from-scratch Flax implementation of
the same scheme:

- no normalization layers anywhere;
- per-block scalar biases before each conv/relu and a scalar multiplier on
  the residual branch;
- the residual branch's *last* conv is zero-initialized, earlier convs are
  He-init scaled by ``L^(-1/(2m-2))`` (m = convs per block, 3 for
  bottleneck), and the classifier is zero-initialized.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from commefficient_tpu.models.layers import (
    Scalar,
    global_avg_pool,
    max_pool,
)


def _scaled_he(num_layers: int, m: int):
    he = nn.initializers.variance_scaling(2.0, "fan_out", "normal")

    def init(key, shape, dtype=jnp.float32):
        return he(key, shape, dtype) * num_layers ** (-1.0 / (2 * m - 2))

    return init


class FixupBottleneck(nn.Module):
    features: int        # planes; output = 4x
    num_layers: int      # total blocks, for init scaling
    stride: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        out_ch = self.features * 4
        init = _scaled_he(self.num_layers, m=3)
        b1a, b1b = Scalar(0.0, name="bias1a")(), Scalar(0.0, name="bias1b")()
        b2a, b2b = Scalar(0.0, name="bias2a")(), Scalar(0.0, name="bias2b")()
        b3a, b3b = Scalar(0.0, name="bias3a")(), Scalar(0.0, name="bias3b")()
        scale = Scalar(1.0, name="scale")()

        y = nn.Conv(self.features, (1, 1), padding="VALID", use_bias=False,
                    kernel_init=init, name="conv1")(x + b1a)
        y = nn.relu(y + b1b)
        y = nn.Conv(self.features, (3, 3), strides=(self.stride, self.stride),
                    padding=1, use_bias=False, kernel_init=init,
                    name="conv2")(y + b2a)
        y = nn.relu(y + b2b)
        y = nn.Conv(out_ch, (1, 1), padding="VALID", use_bias=False,
                    kernel_init=nn.initializers.zeros, name="conv3")(y + b3a)
        y = y * scale + b3b
        if self.stride != 1 or x.shape[-1] != out_ch:
            sc = nn.Conv(out_ch, (1, 1), strides=(self.stride, self.stride),
                         padding="VALID", use_bias=False,
                         name="shortcut")(x + b1a)
        else:
            sc = x
        return nn.relu(y + sc)


class FixupResNetImageNet(nn.Module):
    layers: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    initial_channels: int = 3

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        depth = sum(self.layers)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                    name="stem")(x)
        bias1 = Scalar(0.0, name="bias1")()
        x = nn.relu(x + bias1)
        x = max_pool(x, 3, stride=2, padding=((1, 1), (1, 1)))
        for stage, (planes, n) in enumerate(zip((64, 128, 256, 512),
                                                self.layers)):
            for i in range(n):
                x = FixupBottleneck(
                    planes, depth,
                    stride=2 if stage > 0 and i == 0 else 1,
                    name=f"stage{stage}_block{i}")(x)
        x = global_avg_pool(x)
        bias2 = Scalar(0.0, name="bias2")()
        return nn.Dense(self.num_classes, kernel_init=nn.initializers.zeros,
                        name="fc")(x + bias2)


def FixupResNet50(num_classes: int = 1000, **kw):
    return FixupResNetImageNet(layers=(3, 4, 6, 3), num_classes=num_classes,
                               **kw)
