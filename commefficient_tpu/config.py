"""Configuration for CommEfficient-TPU.

Keeps the reference's flag vocabulary (reference: CommEfficient/utils.py:102-230)
so users of the original framework can carry their invocations over, but stores
everything in a typed, hashable dataclass that can be closed over by ``jax.jit``
(the reference threads an argparse Namespace through every function instead).

TPU-specific additions: ``mesh_shape``/``mesh_axes`` for the device mesh,
``param_dtype``/``compute_dtype`` for bfloat16 compute, and
``max_client_batch`` (static per-client batch bound — XLA needs static shapes
where the reference used dynamic per-client batches).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Tuple

MODES = ("sketch", "true_topk", "local_topk", "fedavg", "uncompressed")
ERROR_TYPES = ("none", "local", "virtual")
DP_MODES = ("worker", "server")
ALERT_ACTIONS = ("log", "warn", "checkpoint", "abort")
# adversarial client injection (data/scenarios.py AdversaryPlan):
# deterministic per-client fates keyed off (seed, client_id)
ADVERSARY_KINDS = ("none", "labelflip", "signflip", "scale", "noise", "nan")
# robust aggregation in transmitted space (core/server.py)
DEFENSES = ("none", "normclip", "trim")
# sketch-table wire dtypes (--wire_dtype; ops/wire.py): what a table
# cell costs on the ICI/upload wire — f32, bf16 rounding, or int8
# block-quantized with stochastic rounding
WIRE_DTYPES = ("float32", "bfloat16", "int8")
# what the round does with a nonfinite per-client update (core/runtime.py)
NONFINITE_ACTIONS = ("abort", "quarantine")

# reference: CommEfficient/utils.py:37-44
FED_DATASETS = {
    "CIFAR10": 10,
    "CIFAR100": 100,
    "EMNIST": 62,
    "ImageNet": 1000,
    "PERSONA": -1,
}


def num_classes_of_dataset(dataset_name: str) -> int:
    return FED_DATASETS[dataset_name]


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Static configuration of a federated run.

    Field names follow the reference flags (CommEfficient/utils.py:102-230);
    ``do_*`` booleans keep the reference's argparse ``dest`` names.
    """

    # meta
    mode: str = "sketch"
    do_test: bool = False
    use_tensorboard: bool = False
    seed: int = 21

    # data / model
    model: str = "ResNet9"
    dataset_name: str = "CIFAR10"
    dataset_dir: str = "./dataset"
    do_finetune: bool = False
    do_checkpoint: bool = False
    checkpoint_path: str = "./checkpoint"
    # per-shard streaming checkpoint writes (peak host memory = one shard);
    # required when the state exceeds checkpoint.DEFAULT_MAX_HOST_BYTES
    checkpoint_sharded: bool = False
    # TPU-native improvement over the reference (which can only save final
    # weights, cv_train.py:418-421): periodic full-FedState checkpoints and
    # exact mid-run resume (see checkpoint.py)
    checkpoint_every: int = 0     # epochs between mid-run checkpoints; 0=off
    do_resume: bool = False
    # opt-in to resuming checkpoints written before params fingerprinting
    # existed (their flat-weight layout cannot be verified; see checkpoint.py)
    resume_unverified: bool = False
    finetune_path: str = "./finetune"
    finetuned_from: Optional[str] = None
    do_batchnorm: bool = False
    # images per class for the synthetic CIFAR fallback (no-network runs);
    # the real pickles/tree take precedence when present
    synthetic_per_class: int = 64
    # non-saturating synthetic regime for time-to-accuracy studies
    # (data/fed_cifar.py _synthetic_cifar hard=True): shared-base
    # prototypes + heavy pixel noise (+ train-only label noise) so a
    # 24-epoch accuracy curve stays well below 100% and keeps climbing
    synthetic_hard: bool = False
    synthetic_label_noise: float = 0.0
    # train WITHOUT data augmentation (normalize-only transform).
    # Implied by --synthetic_hard; needed standalone for any synthetic
    # regime whose class evidence is per-pixel (crop/flip/shift
    # augmentation scrambles prototype pixels and training flatlines at
    # chance — measured on both CIFAR-hard and synthetic EMNIST)
    no_augment: bool = False
    num_results_train: int = 2
    num_results_val: int = 2

    # compression (reference defaults utils.py:142-147)
    k: int = 50_000
    num_cols: int = 500_000
    num_rows: int = 5
    num_blocks: int = 20
    do_topk_down: bool = False
    # pin --num_cols exactly as given. By default (False) the circulant
    # sketch AUTO-SIZES num_cols up to the nearest TPU-efficient value at
    # model-build time (see auto_num_cols): the reference's default
    # c=500,000 was a GPU/csvec choice (utils.py:142-145) that (a) is
    # never 1024-aligned, disqualifying both Pallas kernels, and (b) at
    # GPT-2 scale can exceed the static-roll block budget and fall into
    # the measured ~100x take_along_axis cliff (ops/circulant.py). The
    # rounding grows the upload budget by < 0.3% at flagship sizes; pass
    # --exact_num_cols to reproduce the reference geometry bit-for-bit.
    exact_num_cols: bool = False

    # optimization (reference defaults utils.py:150-162)
    local_momentum: float = 0.9
    virtual_momentum: float = 0.0
    weight_decay: float = 5e-4
    num_epochs: float = 24.0
    num_fedavg_epochs: int = 1
    fedavg_batch_size: int = -1
    fedavg_lr_decay: float = 1.0
    error_type: str = "none"
    lr_scale: Optional[float] = 0.4
    pivot_epoch: float = 5.0
    # GPT-2 LR warmup (TPU-native opt-in; the reference's GPT-2 schedule
    # is linear -> 0 from full LR at step 0): ramp 0 -> lr_scale over
    # pivot_epoch, then linear -> 0. The CV driver always ramps (its
    # reference does); this flag only affects gpt2_train.
    lr_warmup: bool = False

    # federation / parallelization
    num_clients: Optional[int] = None
    num_workers: int = 1          # clients sampled per round
    do_iid: bool = False

    # batching (reference utils.py:190-195)
    local_batch_size: int = 8     # -1 => client's whole dataset
    valid_batch_size: int = 8
    microbatch_size: int = -1     # -1 => whole batch in one fwd/bwd

    # GPT-2 (reference utils.py:183-207)
    model_checkpoint: str = "gpt2"
    num_candidates: int = 2
    max_history: int = 2
    # static packed sequence length for PERSONA (0 = driver default, 280).
    # TPU-native knob: the reference pads per batch dynamically
    # (personachat_collate_fn); static shapes make padding a compile-time
    # cost, so a corpus with short dialogues should set this to its true
    # max length instead of paying 280-token attention on padding
    max_seq_len: int = 0
    lm_coef: float = 1.0
    mc_coef: float = 1.0
    max_grad_norm: Optional[float] = None
    personality_permutations: int = 1
    eval_before_start: bool = False

    # differential privacy (reference utils.py:210-214)
    do_dp: bool = False
    dp_mode: str = "worker"
    l2_norm_clip: float = 1.0
    noise_multiplier: float = 0.0

    # simulated per-client communication byte tracking (the reference always
    # tracks; here it can be disabled for pure-throughput benchmarks)
    track_bytes: bool = True

    # --- TPU-native additions (no reference equivalent) ---
    mesh_shape: Tuple[int, ...] = ()      # () => single device
    mesh_axes: Tuple[str, ...] = ("clients",)
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # static upper bound on a client's dataset size; used to pad
    # `local_batch_size == -1` (whole-client) batches to a fixed shape
    max_client_batch: int = 512
    sketch_seed: int = 42
    # sketch implementation (all are linear (r, c) tables):
    # - "circ" (default): circulant count sketch — count-sketch cell
    #   semantics (stable cell-zeroing error feedback) built from static
    #   rolls instead of scatter/gather: ~30x faster than "hash" on TPU
    #   (ops/circulant.py);
    # - "hash": count sketch with exact CSVec cell semantics (the
    #   reference's own hash family); O(d*r) scatter/gather encode/decode;
    # - "rht": SRHT — signs + Kronecker-Hadamard on the MXU + subsample;
    #   fast but EMPIRICALLY DIVERGENT under FetchSGD error feedback
    #   whenever r*c << d (top-k over uniformly-noisy JL estimates is not
    #   a contraction). Safe only near the lossless regime r*c >= d; the
    #   runtime warns otherwise.
    sketch_impl: str = "circ"
    # opt-in override for the rht compressing-regime hard error (see
    # core/server.py validate_mode_combo): rht at r*c < d measurably
    # diverges under error feedback; this flag exists to reproduce that
    # study, not to train with
    allow_divergent_rht: bool = False
    # DEPRECATED alias of --wire_dtype (kept as a real field: for rht it
    # still selects the transform compute dtype, and pre-PR-14 configs/
    # checkpoints name it). __post_init__ resolves: an empty wire_dtype
    # inherits sketch_dtype, and a bfloat16 wire syncs sketch_dtype so
    # the rht transform compute follows the wire. Parse-time use of
    # --sketch_dtype warns (see parse_args).
    sketch_dtype: str = "float32"
    # sketch-table WIRE dtype ("float32" | "bfloat16" | "int8"; "" =
    # inherit the deprecated --sketch_dtype alias). What a table cell
    # costs on the wire — per-client uploads AND every table-shaped
    # collective:
    # - bfloat16: uploads/psum/psum_scatter payloads travel rounded to
    #   bf16 — half the ICI payload at ~2^-8 relative cell rounding;
    #   server math stays fp32.
    # - int8 (ops/wire.py): uploads quantize with per-column-block
    #   symmetric abs-max scales and STOCHASTIC rounding (unbiased;
    #   draws keyed off (seed, global_round, block) — deterministic and
    #   replay/resume-safe), the mesh table reduce becomes an
    #   all_to_all of int8 column shards + f32 scales with shard-local
    #   dequantize-accumulate in f32 (int8 summation over W clients
    #   would overflow), and the rounding residual is left to the
    #   server error-feedback state. ~0.27x the f32 wire bytes (scales
    #   included; ledger-gated <= 0.30x by dryrun_multichip). Requires
    #   mode=sketch with a table server state (circ/hash impl; on a
    #   mesh additionally the sharded server tail — the quantized
    #   reduce is shard-shaped). Fail-fast on ineligible combinations.
    wire_dtype: str = ""
    # int8 wire quantization granularity: columns per abs-max scale
    # block. Larger = less scale overhead (4/block bytes per cell);
    # smaller = tighter scales. Shrunk automatically to the per-device
    # column shard when the mesh shard is narrower; must then divide it.
    wire_block: int = 256
    # rht row-at-a-time transforms (memory mode): -1 auto (on at dp >= 2^25),
    # 0 force batched, 1 force scanned. bf16 single-vector round-trips fit
    # batched even at GPT-2 scale and run ~2x faster
    sketch_scan_rows: int = -1
    # circulant-sketch pallas kernel policy: "auto" (default) = fused
    # encode AND decode when eligible (TPU, 1024-aligned shifts, VMEM
    # budget — decode measured 21 ms vs 129 ms at d=124M; encode lifts
    # the fused flagship round 76.5k -> 85.2k tok/s), "on" = force-enable
    # (same set; kept for explicitness), "off" = XLA paths only
    pallas: str = "auto"

    # Sketch-mode error-feedback rule (TPU-native extension; the reference
    # only has "zero"):
    # - "zero" (default): the reference's cell-zeroing — re-encode the
    #   k-sparse update and zero every table cell it occupies
    #   (fed_aggregator.py:596-611). Dissipates ~k/c of EVERY coordinate's
    #   accumulated error per row per round (colliding coordinates lose
    #   their whole cell), which under small bounded increments (gradient
    #   clipping) destroys slow-accumulating signal before it can win the
    #   top-k — the measured clip x sketch stall (runs/gpt2_conv/README.md
    #   finding 5).
    # - "subtract": subtract the encoded update from Verror (and the
    #   velocity's estimated values at the support from Vvelocity) —
    #   removes exactly the extracted mass, preserving colliding
    #   coordinates' accumulated error. Equals "zero" bit-for-bit in the
    #   lossless limit (tests/test_core.py TestSketchEFVariants); at real
    #   compression it trades the leak for residual decode noise left in
    #   the table, bounded per round by the (clipped) increment norm.
    sketch_ef: str = "zero"
    # Where the server's momentum/error live in sketch mode (TPU-native
    # extension; the reference always keeps them as (r, c) tables,
    # fed_aggregator.py:568-613):
    # - "table" (default): the reference's FetchSGD — all server state in
    #   table space; EF per --sketch_ef.
    # - "dense": momentum/error kept as dense (d,) pre-images; each round
    #   ONE encode+decode round-trip of the error injects exactly the
    #   compression noise the table channel imposes (the upload is still
    #   the r x c table — byte accounting unchanged), and error feedback /
    #   momentum masking zero the exact update support like true_topk.
    #   Leak-free AND noise-dissipation-free-but-stable (state is exact),
    #   at the cost of O(d) server memory — which the reference's PS
    #   already spends on weights/velocities for every dense mode
    #   (fed_aggregator.py:105-129). Single-device only (on a mesh it
    #   would turn the table-sized psum back into a d-sized one);
    #   requires deferred encode (no per-client table clip — use
    #   --sketch_dense_clip for clipping).
    sketch_server_state: str = "table"
    # Uniform table-space error decay (TPU-native extension): after the
    # round's error feedback, Verror *= error_decay (sketch and true_topk
    # modes). 1.0 = off. A blunt stabilizer for regimes where accumulated
    # table mass dominates fresh gradients; part of the sketch-vs-dense
    # study battery (runs/gpt2_conv/README.md).
    error_decay: float = 1.0

    # TPU-optimized approximate top-k (lax.approx_max_k, 0.95 recall) for
    # the sparsification selects; exact lax.top_k when False
    approx_topk: bool = False
    # profiling: write a jax profiler trace (tensorboard-viewable) of the
    # rounds in --profile_rounds to this directory (the reference's analogue
    # is its cProfile hooks, fed_aggregator.py:46-52)
    profile_dir: str = ""
    # which 1-based global rounds the trace covers, "START:STOP" inclusive
    # (telemetry/profiling.py); the default reproduces the old hardcoded
    # steady-state window, rounds 2-4
    profile_rounds: str = "2:4"
    # run telemetry (telemetry/): telemetry.jsonl event stream in the
    # run's logdir — manifest, per-round records, compile/memory events,
    # NaN diagnostics, end-of-run summary. --no_telemetry disables.
    telemetry: bool = True
    # per-round record granularity: emit a round event every N rounds
    # (0 = none). Each emitted record costs one host sync of the round's
    # metrics (~170 ms on the remote-tunnel runtime, against a ~50 ms
    # steady-state round) — so the default -1 is AUTO: every round under
    # --test (the smoke contract wants round records), every 64 rounds
    # otherwise (~5% overhead worst case instead of several-fold). Set 1
    # explicitly for convergence studies where per-round curves matter.
    telemetry_every: int = -1
    # peak FLOP/s of one accelerator for MFU accounting
    # (telemetry/utilization.py): 0 = look the device_kind up in the
    # built-in per-generation table; set explicitly for chips the table
    # does not know (or to pin a different MFU denominator, e.g. fp32
    # peak on CPU smoke runs)
    peak_flops: float = 0.0
    # peak HBM bandwidth in GB/s for roofline attribution
    # (telemetry/utilization.py): 0 = look the device_kind up in the
    # built-in per-generation table; set explicitly for chips the table
    # does not know. Unknown chip + no override = null roofline fields
    # in the utilization events (never a verdict against a guess).
    peak_hbm_gbps: float = 0.0
    # compression-signal health diagnostics (telemetry/signals.py):
    # cheap on-device norms (aggregated gradient, EF accumulators,
    # update support, sketch collision proxies) computed inside the
    # jitted round and emitted as `signals` telemetry events at the
    # --telemetry_every cadence. --no_signals drops them from the round
    # step entirely (they cost a handful of fused reductions per round,
    # plus two table-sized all-gathers in mesh sketch mode); they are
    # also auto-dropped under --no_telemetry, which leaves no consumer.
    signals: bool = True
    # per-client population statistics (telemetry/clients.py): per-client
    # loss / gradient norms pre+post clip / clip saturation / update-
    # contribution norm / exact bytes, reduced ON DEVICE to quantile
    # summaries along the round's client axis and emitted as schema-v3
    # `client_stats` events at the --telemetry_every cadence (host-side
    # participation ledger included). --no_client_stats drops them from
    # the jitted round; like signals they are also auto-dropped under
    # --no_telemetry (no hot-path work for a stream nobody reads).
    client_stats: bool = True
    # participation-ledger backing (telemetry/population.py): "off" =
    # the exact per-client host dict (O(population) memory and
    # checkpoint sidecar), "on" = the bounded-memory sketch ledger
    # (count-min counts, space-saving heavy hitters, KMV distinct
    # sample, P2 stream quantiles — <= 8 MiB regardless of population),
    # "auto" = exact below 10^5 registered clients, sketch at/above.
    # Event fields are identical in both modes; the `estimated` flag
    # (client_stats + population events, schema v11) says which wrote
    # them — the sketch never fakes exactness.
    population_sketch: str = "auto"
    # online anomaly monitor (telemetry/health.py) action when a rule
    # fires: "log" = alert event only; "warn" = + stderr line;
    # "checkpoint" = + one-shot flight-recorder bundle (FedState snapshot
    # via the checkpoint layer, last-N telemetry events, alert context)
    # into <logdir>/postmortem on the FIRST firing; "abort" = all of the
    # above, then stop training like the NaN abort (summary records
    # aborted=True). The monitor only exists when telemetry is on.
    alert_action: str = "log"
    # rolling-history length (observations) for the monitor's median/MAD
    # z-scores; also the per-rule refire cooldown
    alert_window: int = 32
    # robust z-score threshold for the statistical rules (median/MAD z;
    # 6.0 is deliberately loose — the monitor must stay silent on healthy
    # noisy streams, see tests/test_health.py's false-positive gate)
    alert_zscore: float = 6.0
    # heavy-hitter recovery quality (topk_overlap): compares the
    # decompressed update's support against the exact top-k of the DENSE
    # error — needs a dense reference, so it is opt-in: true_topk /
    # dense-preimage sketch reconstruct it from existing state (one extra
    # O(d) top-k per round); table-state sketch additionally carries a
    # dense shadow error accumulator (2 x O(d) state, single-device
    # deferred-encode only)
    signals_exact: bool = False
    # layer-wise compression attribution (telemetry/layer_signals.py):
    # partition the model pytree into named parameter groups (coarse =
    # path-pattern groups — embed/attn/mlp/norm-bias per block for the
    # GPT-2 layout, stage-level for conv nets; leaf = one group per
    # pytree leaf) and reduce the round's dense quantities per group
    # inside the jitted round — per-group gradient/update/EF mass,
    # top-k support counts, heavy-hitter recovery under
    # --signals_exact. Emitted as schema-v10 `layer_signals` events at
    # the signals cadence; "off" compiles the group machinery out
    # entirely (round HLO byte-identical, tested). Gated exactly like
    # signals: --no_signals / --no_telemetry / async / decode_overlap
    # drop it too. Cost: one (d_pad,) int32 group-id map resident on
    # device (sharded on a mesh — the same O(d) class as the byte
    # accounting's coord_last_update) plus a few segment reductions.
    signal_groups: str = "coarse"
    # fail (instead of warn) on configurations round 5 MEASURED divergent
    # — see core/server.py check_regime_health: local_topk with local
    # error feedback at dense-stable lr, subtract-EF at high collision
    # load. The measurements: runs/README.md (local_topk envelope),
    # runs/gpt2_conv/README.md (subtract dose-response)
    strict_regimes: bool = False
    # persistent XLA compilation cache directory: the GPT-2-scale federated
    # round compiles in ~10 min cold — pay it once per machine, not per run.
    # Flag spelling: --compile_cache (alias --compilation_cache_dir)
    compilation_cache_dir: str = "~/.cache/commefficient_tpu_xla"
    # round input pipeline (core/pipeline.py): prefetch round t+1's client
    # indices + batch on a background thread while round t executes.
    # Bit-identical losses to the inline path (dryrun-asserted — all
    # randomness is keyed by the round index); --no_pipeline reverts to
    # the fully synchronous fetch->dispatch loop
    pipeline: bool = True
    # how many rounds the prefetcher runs ahead (queue bound). 2 =
    # double-buffered: one batch in flight to the device, one staged
    prefetch_depth: int = 2

    # --- async buffered aggregation (core/async_agg.py; FedBuff-style,
    # Nguyen et al. 2022). Off by default: the lockstep round is the
    # reference-parity path. When on, the driver keeps up to
    # ``max_inflight`` cohort computations in flight, merges each
    # cohort's transmitted-space sum into a server-side buffer as it
    # "lands" (simulated arrival order from data/scenarios.py), applies
    # ``staleness_discount`` per merged cohort, and commits the buffered
    # aggregate through the normal server momentum+EF step once
    # ``buffer_goal`` cohorts have merged. Sound only for modes whose
    # server consumes the cohort uploads purely through their weighted
    # SUM — no per-client persistent rows, no topk_down (see
    # core/async_agg.validate_async_combo, which fails fast otherwise).
    async_agg: bool = False
    # cohorts kept in flight (K). Dispatching past K forces the
    # earliest in-flight cohort to land first — the simulated "pool is
    # full" wait. Each in-flight cohort holds one transmitted-space
    # array on device.
    max_inflight: int = 4
    # cohorts merged per commit (M). 1 commits every landing cohort;
    # with max_inflight 1 and no scenario latency that reduces exactly
    # to the synchronous round (bit-identical, dryrun-asserted).
    buffer_goal: int = 1
    # staleness discount applied to a cohort merged s commits after its
    # dispatch: "none" = 1, "poly" = (1+s)^-alpha (FedBuff's default
    # shape; alpha 0.5 reproduces its 1/sqrt(1+s)), "exp" =
    # exp(-alpha*s). All rules give weight exactly 1.0 at s=0.
    staleness_discount: str = "poly"
    staleness_alpha: float = 0.5

    # --- straggler scenario engine (data/scenarios.py): per-cohort
    # simulated latency / dropout / dynamic partial participation,
    # seeded deterministically off (seed, global round index) so runs
    # replay exactly. Only meaningful with --async_agg (the lockstep
    # loop has no notion of a late cohort) — configuring a scenario
    # without it fails fast instead of silently doing nothing.
    scenario: str = "none"          # none | uniform | lognormal | stragglers
    scenario_latency: float = 1.0   # base latency, in cohort-dispatch ticks
    scenario_spread: float = 0.5    # uniform half-width / lognormal sigma
    scenario_straggler_frac: float = 0.1   # "stragglers" kind: slow fraction
    scenario_straggler_mult: float = 10.0  # ... and their latency multiplier
    scenario_dropout: float = 0.0   # per-cohort probability of never landing
    scenario_participation: float = 1.0  # fraction of worker slots kept
    # --- adversarial client injection (data/scenarios.py AdversaryPlan).
    # A deterministic --adversary_frac fraction of the client universe is
    # hostile, keyed off (seed, client_id) — the same client misbehaves
    # every time it is sampled, across resumes and prefetch interleavings.
    # Kinds: labelflip (train on (C-1)-y — data space, needs a
    # classification dataset), signflip (upload x -1), scale (upload
    # x adversary_scale — the boosted/model-replacement attack), noise
    # (upload + adversary_scale * N(0, I) in transmitted space), nan
    # (upload all-NaN — the broken-client case --nonfinite_action
    # handles). Unlike the latency scenario, injection works in BOTH the
    # synchronous and async rounds (it acts at cohort compute, which both
    # paths share).
    adversary: str = "none"
    adversary_frac: float = 0.0
    # scale attack multiplier / noise attack sigma
    adversary_scale: float = 10.0
    # --- robust aggregation in transmitted space (core/server.py):
    # - normclip: per-client update-norm clipping to a robust threshold —
    #   rolling-median of past rounds' median per-datum update norms
    #   (defense_window rounds, FedState.defense_ref) x defense_clip_mult
    #   (Sun et al. 2019). Sound in table space too: an l2 clip is a
    #   rescaling, and rescaling commutes with the linear sketch.
    # - trim: per-coordinate trimmed-mean aggregation — drop the
    #   defense_trim_frac highest and lowest per-client values per
    #   coordinate, average the rest uniformly (Yin et al. 2018). Single
    #   device only (the cross-client sort needs every client's full
    #   vector in one place; on a mesh use normclip).
    # Off by default; the defended round's HLO is byte-identical to the
    # pre-defense round when off (same discipline as signals).
    defense: str = "none"
    defense_clip_mult: float = 3.0
    defense_window: int = 8
    defense_trim_frac: float = 0.1
    # --- nonfinite recovery (core/runtime.py + core/quarantine.py):
    # - abort (default): the pre-existing behavior — the first nonfinite
    #   per-client update poisons the aggregate, the device flag fires,
    #   the run stops at the epoch boundary.
    # - quarantine: the nonfinite client's upload is zeroed OUT of the
    #   aggregate inside the jitted round (its datum count and metrics
    #   contributions too), the client id is logged to a host-side
    #   QuarantineLedger, and the client is benched for
    #   quarantine_backoff rounds, retried, and permanently ejected
    #   after quarantine_strikes strikes. A FULLY-nonfinite round (no
    #   finite client left) still aborts. Costs one (W,)-bool host fetch
    #   per round for the ledger.
    nonfinite_action: str = "abort"
    quarantine_backoff: int = 8
    quarantine_strikes: int = 3
    # --- preemption / fault tolerance (core/preempt.py) ---
    # graceful-preemption drain budget, seconds: on SIGTERM/SIGINT the
    # driver loop finishes the in-flight round, drains the input
    # pipeline / async pool (flushing any open buffer through the
    # epoch-flush path), writes an out-of-cadence checkpoint tagged
    # `preempt` (round-granular meta, so the resume is exact), emits a
    # final `fault` telemetry event and exits 0 — all within this
    # budget. A SECOND signal force-exits immediately. Must be > 0.
    preempt_grace: float = 30.0
    # host-side hang watchdog (core/preempt.RoundWatchdog): arms a
    # deadline around each round's dispatch+sync, derived from the
    # rolling median round time (MAD-floored like the health.py rules)
    # x watchdog_mult. On expiry it fires a critical `round_stall`
    # alert through the AnomalyMonitor and records an events-only
    # flight-recorder bundle (the state fetch itself could hang). Also
    # arms bounded exponential-backoff RETRIES around the retryable
    # host-side input phases (device_put / gather dispatch). Off by
    # default: it adds a thread and retry semantics the lockstep tests
    # must opt into.
    watchdog: bool = False
    # stall deadline = watchdog_mult x (rolling median + MAD envelope);
    # must be >= 1 (a sub-1 multiplier would declare the MEDIAN round
    # stalled)
    watchdog_mult: float = 10.0
    # fixed run directory for telemetry/tensorboard artifacts; empty =
    # the timestamped make_logdir default. A resumed run pointed at its
    # predecessor's logdir APPENDS to the existing events.jsonl with a
    # `resume` lineage record (telemetry/run.py) instead of clobbering
    # it.
    logdir: str = ""

    # rematerialize transformer blocks on backward (memory/FLOPs trade)
    do_remat: bool = False
    # selective-remat policy (jax.checkpoint_policies attribute name, e.g.
    # dots_with_no_batch_dims_saveable) applied when do_remat; "" = full
    remat_policy: str = ""
    # chunked LM cross-entropy: compute vocab logits ``lm_chunk`` tokens at
    # a time under jax.checkpoint instead of materializing the full
    # (tokens, vocab) fp32 tensor (+ cotangent) — the GPT-2 microbatch-8
    # memory enabler (losses._chunked_lm_nll). 0 = dense
    lm_chunk: int = 0
    # GPT-2 attention implementation: "auto" (default — dense below
    # S=1024, flash above, the measured crossover on v5e:
    # scripts/bench_longctx.py), "dense" (materialized logits), "flash"
    # (fused TPU Pallas kernel, O(S) attention memory; falls back to
    # dense off-TPU/unaligned S)
    attn_impl: str = "auto"
    # sketch-mode worker-gradient clipping (TPU-native extension): apply
    # --max_grad_norm to the DENSE per-client gradient before encoding
    # (threshold x num_iters, the same semantics as the dense modes)
    # instead of the reference's post-encode table clip
    # (fed_worker.py:318-319, bare threshold). Because an l2 clip is a
    # rescaling and the encode is linear, the two placements apply the
    # SAME operation at a matched threshold (pinned by
    # test_sketch_dense_clip_wiring); this flag aligns the threshold
    # semantics across modes. Measured finding (runs/gpt2_conv/
    # README.md): clipping that rescues the dense modes degrades
    # sketch-mode error feedback at every measured threshold — prefer
    # unclipped sketch on from-scratch regimes. Disables the
    # fused-clients fast path (the clip is per-client); deferred encode
    # survives (clipped dense gradients still sum before one encode).
    sketch_dense_clip: bool = False
    # Fused sketch encode (core/client.py): encode each per-microbatch
    # gradient straight into the (r, c) Count Sketch table inside the
    # microbatch scan — the scan carry is the table, so the dense (d,)
    # gradient SUM never materializes in HBM (at GPT-2 124M the scan
    # carry pair alone is ~1 GB of temp). Sound exactly when the encode
    # deferral is sound AND nothing downstream consumes the dense
    # per-client/aggregate gradient:
    # - "auto" (default): engage when eligible, silently fall back to
    #   the unfused path otherwise (numerics never change silently —
    #   the fallback IS the old path);
    # - "on": require it — fail fast with the blocking reason
    #   (--sketch_dense_clip, DP clip+noise, --signals_exact's dense
    #   shadow accumulator, the single-device signals dense capture,
    #   a defense that clips dense per-client norms, the rht impl,
    #   per-client grad stats on the vmap path);
    # - "off": never (the pre-fusion round, bit-identical HLO).
    # See README "Fused sketch encode" for the soundness matrix.
    sketch_fused_encode: str = "auto"
    # Split the federated round into two executables — the client block
    # (cohort compute + table sum) and the server block (decode /
    # top-k uncompress + weight update) — so the server decode of round
    # t is dispatched as its own program and runs while the host (and
    # the input pipeline) stage round t+1's client block, and a
    # record-cadence metrics sync completes when the CLIENT half
    # finishes instead of waiting out the decode. Losses are
    # bit-identical to the monolithic round (dryrun-asserted; the split
    # reuses the async cohort/commit machinery at K=1/M=1, which PR 6
    # proved bitwise). Same soundness constraints as --async_agg (no
    # per-client persistent rows, no topk_down) — unsound combos fail
    # fast. Mutually exclusive with --async_agg (which already splits).
    decode_overlap: bool = False
    # Sharded sketch SERVER tail (core/server.py
    # sharded_sketch_server_update): on a mesh, replace the round's
    # replicated table psum with a psum_scatter over table columns
    # (each device owns c/n columns of the momentum/EF state — the
    # dense-mode reduce_scatter analogue), re-gather the small (r, c)
    # error table, range-decode only the device's d_pad/n coordinate
    # slice, take a local top-k and merge an (n, k)-sized candidate
    # all-gather into the global top-k — no device ever materializes
    # the dense (d,) decode estimates, so per-device server temp drops
    # from O(d) to O(d/n + n*k):
    # - "auto" (default): engage on an eligible mesh (table-state
    #   sketch, no seq axis, num_cols divisible by the mesh size),
    #   silently fall back to the replicated tail otherwise (the
    #   fallback IS the pre-sharding round — numerics never change
    #   silently);
    # - "on": require it — fail fast listing every blocker;
    # - "off": never (the replicated server tail, for A/B gates).
    sketch_sharded_server: str = "auto"
    # jointly-computed round gradient (core/client.py make_fused_grad):
    # when no per-client nonlinearity exists, accumulate the round's
    # aggregate into ONE (d,) buffer instead of vmap's per-client (W, d)
    # gradient. Exact up to summation order; measured ~15% off the
    # flagship GPT-2 round. Auto-disabled when ineligible (local state,
    # clip, DP, topk_down, fedavg/local_topk, seq sharding, straddling
    # microbatches); this flag forces the vmap path everywhere.
    fused_clients: bool = True

    # filled in at model-build time, like the reference's args.grad_size
    # (fed_aggregator.py:88). Frozen dataclass => use `replace`.
    grad_size: int = 0

    def __post_init__(self):
        # normalize the documented implication once, so every consumer
        # can read cfg.no_augment directly (a hard-regime run that
        # re-enabled augmentation would flatline at chance)
        if self.synthetic_hard and not self.no_augment:
            object.__setattr__(self, "no_augment", True)
        assert self.mode in MODES, self.mode
        assert self.error_type in ERROR_TYPES, self.error_type
        assert self.dp_mode in DP_MODES, self.dp_mode
        assert self.pallas in ("auto", "on", "off"), self.pallas
        assert self.sketch_ef in ("zero", "subtract"), self.sketch_ef
        assert self.sketch_server_state in ("table", "dense"), \
            self.sketch_server_state
        assert 0.0 < self.error_decay <= 1.0, self.error_decay
        if self.error_decay < 1.0:
            # silently ignoring the flag would let a decay study run
            # undecayed (same fail-fast rationale as sketch_dense_clip)
            assert self.mode in ("sketch", "true_topk"), \
                "--error_decay only applies to modes with virtual error " \
                "(sketch, true_topk)"
        assert self.attn_impl in ("auto", "dense", "flash"), self.attn_impl
        # ---- wire dtype resolution (--wire_dtype generalizes the
        # deprecated --sketch_dtype alias; see the field comments)
        assert self.sketch_dtype in ("float32", "bfloat16"), \
            self.sketch_dtype
        if self.wire_dtype == "":
            object.__setattr__(self, "wire_dtype", self.sketch_dtype)
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"--wire_dtype {self.wire_dtype!r} not in {WIRE_DTYPES}")
        if self.wire_dtype == "bfloat16" and self.sketch_dtype != "bfloat16":
            # keep the rht transform compute dtype following the wire,
            # exactly as --sketch_dtype bfloat16 always did
            object.__setattr__(self, "sketch_dtype", "bfloat16")
        if self.wire_dtype == "float32" and self.sketch_dtype != "float32":
            # an EXPLICIT f32 wire wins over the deprecated bf16 alias
            # (the empty-wire inheritance above already ran, so a
            # float32 here was requested, not defaulted): leaving
            # sketch_dtype at bf16 would keep the runtime's bf16 wire
            # armed while wire_dtype/telemetry/byte accounting all claim
            # f32. An rht user wanting the bf16 TRANSFORM passes the
            # alias alone — the wire then inherits bf16, as it always
            # did.
            object.__setattr__(self, "sketch_dtype", "float32")
        if self.wire_dtype == "int8" and self.sketch_dtype != "float32":
            # an explicit int8 wire WINS over the deprecated bf16 alias
            # (leaving sketch_dtype at bf16 would arm the runtime's bf16
            # rounding branch, which shadows the int8 wire on the
            # per-client/single-device paths while the byte accounting
            # reports int8 — the silently-wrong-wire failure this
            # resolution exists to prevent; rht, the only other
            # consumer of sketch_dtype, is rejected with int8 below)
            object.__setattr__(self, "sketch_dtype", "float32")
        if self.wire_block < 8:
            raise ValueError(
                f"--wire_block {self.wire_block} must be >= 8: each block "
                "pays 4 bytes of f32 scale, so blocks below 8 columns "
                "spend more on scales than a bf16 wire spends on cells")
        if self.wire_dtype == "int8":
            # fail fast on combinations the quantized wire cannot serve
            # (the silently-ignored-flag contract); topology-dependent
            # blockers (mesh without the sharded server tail, the
            # dense-preimage auto path) fail at runtime init where the
            # mesh is resolved
            if self.mode != "sketch":
                raise ValueError(
                    f"--wire_dtype int8 requires --mode sketch (mode="
                    f"{self.mode} has no table-shaped wire to quantize; "
                    "dense-mode payloads keep their f32 wire)")
            if self.sketch_impl == "rht":
                raise ValueError(
                    "--wire_dtype int8 is unsupported with sketch_impl="
                    "rht: its dense transform has no cell-addressable "
                    "table to block-quantize (use circ or hash)")
            if self.sketch_server_state == "dense":
                raise ValueError(
                    "--wire_dtype int8 is unsupported with "
                    "--sketch_server_state dense: that server path "
                    "consumes the dense aggregated gradient, so no table "
                    "crosses the wire to quantize")
        assert self.sketch_fused_encode in ("auto", "on", "off"), \
            self.sketch_fused_encode
        if self.sketch_fused_encode == "on" and self.mode != "sketch":
            raise ValueError(
                f"--sketch_fused_encode on requires --mode sketch (mode="
                f"{self.mode} has no sketch encode to fuse); drop the flag "
                "or use --sketch_fused_encode auto (a no-op off sketch "
                "mode)")
        assert self.sketch_sharded_server in ("auto", "on", "off"), \
            self.sketch_sharded_server
        if self.sketch_sharded_server == "on" and self.mode != "sketch":
            raise ValueError(
                f"--sketch_sharded_server on requires --mode sketch (mode="
                f"{self.mode} has no sketch server tail to shard); drop "
                "the flag or use --sketch_sharded_server auto (a no-op "
                "off sketch mode)")
        if self.decode_overlap and self.async_agg:
            raise ValueError(
                "--decode_overlap and --async_agg are mutually exclusive: "
                "async buffered aggregation already splits the round into "
                "cohort and commit executables (and adds buffering "
                "semantics on top). Drop one of the flags.")
        if self.signal_groups not in ("coarse", "leaf", "off"):
            raise ValueError(
                f"--signal_groups {self.signal_groups!r} not in "
                "('coarse', 'leaf', 'off')")
        if self.population_sketch not in ("auto", "on", "off"):
            raise ValueError(
                f"--population_sketch {self.population_sketch!r} not in "
                "('auto', 'on', 'off')")
        assert self.telemetry_every >= -1, self.telemetry_every
        assert self.alert_action in ALERT_ACTIONS, self.alert_action
        assert self.alert_window >= 4, self.alert_window
        assert self.alert_zscore > 0, self.alert_zscore
        if self.pipeline and self.prefetch_depth < 1:
            # depth < 1 with pipelining on used to silently degrade to the
            # inline fetch (RoundPipeline treated depth<=0 as "threading
            # off") — a user asking for prefetch would get none and no
            # message. Fail with the fix spelled out instead.
            raise ValueError(
                f"--prefetch_depth {self.prefetch_depth} is invalid with "
                "the round input pipeline enabled: the prefetcher needs a "
                "queue bound of at least 1 (2 = double-buffered). Pass "
                "--prefetch_depth >= 1, or --no_pipeline to run the fetch "
                "inline.")
        if self.prefetch_depth < 1:
            raise ValueError(
                f"--prefetch_depth {self.prefetch_depth} must be >= 1")
        # async buffered aggregation (mode-compatibility guards live in
        # core/async_agg.validate_async_combo, next to validate_mode_combo)
        assert self.staleness_discount in ("none", "poly", "exp"), \
            self.staleness_discount
        assert self.staleness_alpha > 0, self.staleness_alpha
        if self.async_agg:
            if self.buffer_goal < 1:
                raise ValueError(
                    f"--buffer_goal {self.buffer_goal} must be >= 1")
            if self.max_inflight < 1:
                raise ValueError(
                    f"--max_inflight {self.max_inflight} must be >= 1")
        assert self.scenario in ("none", "uniform", "lognormal",
                                 "stragglers"), self.scenario
        assert 0.0 <= self.scenario_dropout < 1.0, self.scenario_dropout
        assert 0.0 < self.scenario_participation <= 1.0, \
            self.scenario_participation
        if not self.async_agg and (
                self.scenario != "none" or self.scenario_dropout > 0
                or self.scenario_participation < 1.0):
            # a scenario without async aggregation would silently do
            # nothing — the lockstep loop never consults it (the exact
            # silently-ignored-flag failure the repo fails fast on)
            raise ValueError(
                "--scenario/--scenario_dropout/--scenario_participation "
                "require --async_agg: the synchronous round loop has no "
                "notion of a late, dropped or partially-participating "
                "cohort, so the scenario would be silently ignored.")
        # adversarial injection / defense / quarantine (the robustness
        # subsystem): validate the numerics here, mode/topology
        # compatibility at runtime init (core/server.validate_defense_combo
        # needs the resolved mesh)
        assert self.adversary in ADVERSARY_KINDS, self.adversary
        assert self.defense in DEFENSES, self.defense
        assert self.nonfinite_action in NONFINITE_ACTIONS, \
            self.nonfinite_action
        if not 0.0 <= self.adversary_frac <= 1.0:
            raise ValueError(
                f"--adversary_frac {self.adversary_frac} must be in [0, 1]")
        if self.adversary != "none" and self.adversary_frac == 0.0:
            # an attack study with zero adversaries would silently
            # measure a clean run (the silently-ignored-flag contract)
            raise ValueError(
                f"--adversary {self.adversary} with --adversary_frac 0 "
                "injects nothing; pass --adversary_frac > 0 (fraction of "
                "the client universe that is hostile)")
        if self.adversary == "none" and self.adversary_frac > 0.0:
            raise ValueError(
                f"--adversary_frac {self.adversary_frac} without "
                "--adversary selects clients that then do nothing; pass "
                f"--adversary {{{','.join(ADVERSARY_KINDS[1:])}}}")
        if self.adversary_scale <= 0:
            raise ValueError(
                f"--adversary_scale {self.adversary_scale} must be > 0 "
                "(scale attack multiplier / noise sigma)")
        if self.defense_clip_mult <= 0:
            raise ValueError(
                f"--defense_clip_mult {self.defense_clip_mult} must be > 0")
        if self.defense_window < 1:
            raise ValueError(
                f"--defense_window {self.defense_window} must be >= 1")
        if not 0.0 <= self.defense_trim_frac < 0.5:
            raise ValueError(
                f"--defense_trim_frac {self.defense_trim_frac} must be in "
                "[0, 0.5): trimming half or more of the clients per side "
                "leaves nothing to average")
        if self.quarantine_backoff < 1:
            raise ValueError(
                f"--quarantine_backoff {self.quarantine_backoff} must be "
                ">= 1 (rounds a struck client sits out before a retry)")
        if self.quarantine_strikes < 1:
            raise ValueError(
                f"--quarantine_strikes {self.quarantine_strikes} must be "
                ">= 1 (strikes before permanent ejection)")
        # preemption / watchdog numerics (validated unconditionally, the
        # scenario/defense-validator pattern: a bad value must fail at
        # parse time, not when the first SIGTERM arrives)
        if self.preempt_grace <= 0:
            raise ValueError(
                f"--preempt_grace {self.preempt_grace} must be > 0 "
                "seconds (the graceful-drain budget after the first "
                "SIGTERM/SIGINT; a second signal always force-exits)")
        if self.watchdog_mult < 1:
            raise ValueError(
                f"--watchdog_mult {self.watchdog_mult} must be >= 1: the "
                "stall deadline is this multiple of the rolling median "
                "round time, and a sub-1 multiplier would declare the "
                "median round stalled")
        if self.watchdog and (not self.telemetry
                              or self.telemetry_every == 0):
            # the deadline history only fills on synced (record) rounds
            # and the stall alert lands in the stream: without telemetry
            # (or with records disabled) the watchdog would silently
            # never arm — the exact silently-ignored-flag failure this
            # repo fails fast on
            raise ValueError(
                "--watchdog requires telemetry round records to arm "
                "(its deadline history fills on synced record rounds "
                "and its round_stall alert goes to the stream): drop "
                "--no_telemetry / set --telemetry_every != 0, or drop "
                "--watchdog.")
        if self.profile_dir:
            # a bad window spec must fail at startup, not at round START
            from commefficient_tpu.telemetry.profiling import \
                parse_profile_rounds
            parse_profile_rounds(self.profile_rounds)
        if self.sketch_dense_clip:
            # silently ignoring the flag would let a clip study run
            # unclipped — the exact wrong-conclusion failure it exists
            # to prevent
            assert self.mode == "sketch" and self.max_grad_norm is not None, \
                "--sketch_dense_clip requires --mode sketch and " \
                "--max_grad_norm"
        if self.mode == "fedavg":
            # reference invariants: utils.py:225-228
            assert self.local_batch_size == -1
            assert self.local_momentum == 0
            assert self.error_type == "none"

    def replace(self, **kw) -> "FedConfig":
        return dataclasses.replace(self, **kw)

    @property
    def telemetry_round_every(self) -> int:
        """Resolved --telemetry_every (-1 = auto; see the field comment):
        per-round records under --test, every 64 rounds otherwise."""
        if self.telemetry_every != -1:
            return self.telemetry_every
        return 1 if self.do_test else 64

    @property
    def transmitted_shape(self) -> Tuple[int, ...]:
        """Shape of the quantity a client uploads (reference: fed_aggregator.py:116-121)."""
        if self.mode == "sketch":
            return (self.num_rows, self.num_cols)
        return (self.grad_size,)

    @property
    def upload_floats(self) -> int:
        """Floats uploaded per participating client per round
        (reference byte table: fed_aggregator.py:291-299)."""
        return {
            "uncompressed": self.grad_size,
            "true_topk": self.grad_size,
            "local_topk": self.k,
            "sketch": self.num_rows * self.num_cols,
            "fedavg": self.grad_size,
        }[self.mode]

    def upload_wire_bytes(self, block: Optional[int] = None) -> float:
        """Exact simulated per-client upload bytes under the wire dtype
        (the paper's first-class metric; reference byte table
        fed_aggregator.py:291-299 counted 4 bytes/float).

        float32 (and every non-sketch mode): 4 bytes per transmitted
        float — byte-identical to the pre-wire accounting. bfloat16:
        2 bytes per table cell. int8: 1 byte per cell PLUS 4 bytes of
        f32 scale per ``block`` cells per row (``block`` defaults to
        cfg.wire_block; the runtime passes its resolved effective block
        so the accounting matches what actually crosses the wire).
        """
        if self.mode != "sketch" or self.wire_dtype == "float32":
            return 4.0 * self.upload_floats
        cells = self.num_rows * self.num_cols
        if self.wire_dtype == "bfloat16":
            return 2.0 * cells
        b = int(block or self.wire_block)
        n_scales = self.num_rows * (-(-self.num_cols // b))
        return float(cells + 4 * n_scales)

    @property
    def needs_client_velocities(self) -> bool:
        # reference: fed_aggregator.py:127-129
        return self.local_momentum > 0

    @property
    def needs_client_errors(self) -> bool:
        # reference: fed_aggregator.py:124-126
        return self.error_type == "local"

    def default_num_clients(self) -> int:
        if self.num_clients is not None:
            return self.num_clients
        # reference hardcoded table: fed_aggregator.py:68-72. Like the
        # reference, fail loudly (KeyError) for datasets with no natural
        # client count (e.g. ImageNet) instead of inventing one.
        defaults = {"EMNIST": 3500, "PERSONA": 17568,
                    "CIFAR10": 10, "CIFAR100": 100}
        return defaults[self.dataset_name]


def auto_num_cols(num_cols: int) -> int:
    """TPU-efficient sketch width for the circulant impl (VERDICT r4 weak
    #1): round ``num_cols`` up to the next multiple of 1024 (vreg-aligned
    shifts => both Pallas kernels eligible, ops/circulant_pallas.py) —
    but ONLY when the rounding grows the user's upload budget by <= 5%
    (at the reference default 500,000 -> 500,736 it is +0.15%). Small
    deliberately-tiny tables (e.g. unit-test geometries like c=320, where
    +1024 would triple the budget and change the compression regime) are
    left untouched. The extreme-d/c gather cliff keeps its loud warning
    (ops/circulant.py make_circulant_sketch) rather than an automatic
    multi-x budget increase. ``--exact_num_cols`` bypasses this entirely.
    """
    align = 1024
    c = -(-num_cols // align) * align
    if c != num_cols and (c - num_cols) / num_cols > 0.05:
        return num_cols
    return c


def enable_compilation_cache(cfg: "FedConfig") -> None:
    """Persistent XLA compile cache (the GPT-2-scale round compiles in ~10
    minutes cold; cache it per machine). Best-effort: unavailable backends
    or read-only filesystems silently skip."""
    enable_compilation_cache_dir(cfg.compilation_cache_dir)


def enable_compilation_cache_dir(cache_dir: str) -> None:
    """Path-form of :func:`enable_compilation_cache` for callers without a
    FedConfig in hand (the bench scripts' ``--compile_cache`` flag)."""
    if not cache_dir:
        return
    try:
        import os

        import jax
        path = os.path.expanduser(cache_dir)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)
    except Exception as e:  # pragma: no cover
        print(f"WARNING: compilation cache disabled ({e})")


def add_args(parser: argparse.ArgumentParser, default_lr: Optional[float] = None):
    """Reference flag surface (CommEfficient/utils.py:102-230), minus the
    CUDA/process plumbing flags (--port, --device, --num_devices,
    --share_ps_gpu, dataloader workers) that have no meaning in a
    single-program SPMD runtime; plus TPU mesh flags."""
    p = parser
    p.add_argument("--test", action="store_true", dest="do_test")
    p.add_argument("--mode", choices=MODES, default="sketch")
    p.add_argument("--tensorboard", dest="use_tensorboard", action="store_true")
    p.add_argument("--seed", type=int, default=21)

    p.add_argument("--model", default="ResNet9")
    p.add_argument("--finetune", action="store_true", dest="do_finetune")
    p.add_argument("--checkpoint", action="store_true", dest="do_checkpoint")
    p.add_argument("--checkpoint_path", type=str, default="./checkpoint")
    p.add_argument("--checkpoint_sharded", action="store_true")
    p.add_argument("--checkpoint_every", type=int, default=0)
    p.add_argument("--resume", action="store_true", dest="do_resume")
    p.add_argument("--resume_unverified", action="store_true")
    p.add_argument("--finetune_path", type=str, default="./finetune")
    p.add_argument("--finetuned_from", type=str, choices=list(FED_DATASETS))
    p.add_argument("--num_results_train", type=int, default=2)
    p.add_argument("--num_results_val", type=int, default=2)
    p.add_argument("--dataset_name", type=str, default="CIFAR10",
                   choices=list(FED_DATASETS))
    p.add_argument("--dataset_dir", type=str, default="./dataset")
    p.add_argument("--batchnorm", action="store_true", dest="do_batchnorm")
    p.add_argument("--synthetic_per_class", type=int, default=64)
    p.add_argument("--synthetic_hard", action="store_true")
    p.add_argument("--synthetic_label_noise", type=float, default=0.0)
    p.add_argument("--no_augment", action="store_true",
                   help="train normalize-only (no crop/flip/shift); "
                        "implied by --synthetic_hard")

    p.add_argument("--k", type=int, default=50_000)
    p.add_argument("--num_cols", type=int, default=500_000)
    p.add_argument("--num_rows", type=int, default=5)
    p.add_argument("--num_blocks", type=int, default=20)
    p.add_argument("--topk_down", action="store_true", dest="do_topk_down")
    p.add_argument("--exact_num_cols", action="store_true",
                   help="pin --num_cols exactly (skip the TPU-efficient "
                        "auto-rounding of the circulant sketch width)")

    p.add_argument("--local_momentum", type=float, default=0.9)
    p.add_argument("--virtual_momentum", type=float, default=0.0)
    p.add_argument("--weight_decay", type=float, default=5e-4)
    p.add_argument("--num_epochs", type=float, default=24)
    p.add_argument("--num_fedavg_epochs", type=int, default=1)
    p.add_argument("--fedavg_batch_size", type=int, default=-1)
    p.add_argument("--fedavg_lr_decay", type=float, default=1.0)
    p.add_argument("--error_type", choices=ERROR_TYPES, default="none")
    p.add_argument("--lr_scale", type=float, default=default_lr)
    p.add_argument("--pivot_epoch", type=float, default=5)
    p.add_argument("--lr_warmup", action="store_true",
                   help="GPT-2 only: linear 0 -> lr_scale warmup peaking "
                        "at --pivot_epoch (the reference starts at full "
                        "LR; see gpt2_train.make_gpt2_schedule)")

    p.add_argument("--num_clients", type=int)
    p.add_argument("--num_workers", type=int, default=1)
    p.add_argument("--iid", action="store_true", dest="do_iid")

    p.add_argument("--model_checkpoint", type=str, default="gpt2")
    p.add_argument("--num_candidates", type=int, default=2)
    p.add_argument("--max_history", type=int, default=2)
    p.add_argument("--max_seq_len", type=int, default=0,
                   help="PERSONA packed sequence length; 0 = driver default")
    p.add_argument("--local_batch_size", type=int, default=8)
    p.add_argument("--valid_batch_size", type=int, default=8)
    p.add_argument("--microbatch_size", type=int, default=-1)
    p.add_argument("--lm_coef", type=float, default=1.0)
    p.add_argument("--mc_coef", type=float, default=1.0)
    p.add_argument("--max_grad_norm", type=float)
    p.add_argument("--personality_permutations", type=int, default=1)
    p.add_argument("--eval_before_start", action="store_true")

    p.add_argument("--dp", action="store_true", dest="do_dp")
    p.add_argument("--dp_mode", choices=DP_MODES, default="worker")
    p.add_argument("--l2_norm_clip", type=float, default=1.0)
    p.add_argument("--noise_multiplier", type=float, default=0.0)

    p.add_argument("--no_track_bytes", dest="track_bytes",
                   action="store_false", default=True)

    # TPU-native
    p.add_argument("--mesh_shape", type=str, default="",
                   help="comma-separated mesh, e.g. '4,2'; empty = single device")
    p.add_argument("--mesh_axes", type=str, default="clients")
    p.add_argument("--compute_dtype", type=str, default="bfloat16")
    p.add_argument("--param_dtype", type=str, default="float32")
    p.add_argument("--max_client_batch", type=int, default=512)
    p.add_argument("--sketch_seed", type=int, default=42)
    p.add_argument("--allow_divergent_rht", action="store_true")
    p.add_argument("--sketch_impl", choices=("circ", "hash", "rht"),
                   default="circ")
    p.add_argument("--sketch_dtype", choices=("float32", "bfloat16"),
                   default=None,
                   help="DEPRECATED alias of --wire_dtype (parse-time "
                        "warning; kept for old invocations — rht "
                        "transform compute dtype still follows it)")
    p.add_argument("--wire_dtype", choices=WIRE_DTYPES, default="",
                   help="sketch-table wire dtype: float32 (default), "
                        "bfloat16 (half the table payload, ~2^-8 cell "
                        "rounding), or int8 (block-quantized with "
                        "stochastic rounding + f32 scales, ~0.27x the "
                        "f32 wire; residual absorbed by server EF — "
                        "see ops/wire.py)")
    p.add_argument("--wire_block", type=int, default=256,
                   help="int8 wire: columns per abs-max scale block "
                        "(scale overhead = 4/block bytes per cell)")
    p.add_argument("--sketch_scan_rows", type=int, default=-1,
                   choices=(-1, 0, 1))
    p.add_argument("--pallas", choices=("auto", "on", "off"), default="auto",
                   help="circulant-sketch pallas kernels: auto/on = fused "
                        "encode+decode when eligible, off = XLA paths only")
    p.add_argument("--sketch_ef", choices=("zero", "subtract"),
                   default="zero",
                   help="sketch error-feedback rule: zero = reference "
                        "cell-zeroing; subtract = remove exactly the "
                        "extracted estimates (no collateral cell loss)")
    p.add_argument("--sketch_server_state", choices=("table", "dense"),
                   default="table",
                   help="sketch-mode server momentum/error: table = "
                        "reference FetchSGD (r x c state); dense = (d,) "
                        "pre-images with exact-support EF and one "
                        "enc+dec noise round-trip (single device, "
                        "deferred encode only; upload unchanged)")
    p.add_argument("--error_decay", type=float, default=1.0,
                   help="multiply Verror by this factor each round after "
                        "error feedback (sketch/true_topk); 1.0 = off")
    p.add_argument("--approx_topk", action="store_true")
    p.add_argument("--profile_dir", type=str, default="")
    p.add_argument("--profile_rounds", type=str, default="2:4",
                   help="1-based inclusive round window for the profiler "
                        "trace, START:STOP (with --profile_dir)")
    p.add_argument("--no_telemetry", dest="telemetry", action="store_false",
                   default=True,
                   help="disable the telemetry.jsonl event stream")
    p.add_argument("--telemetry_every", type=int, default=-1,
                   help="emit a per-round telemetry record every N rounds "
                        "(each record syncs the round's metrics to host; "
                        "0 = none, -1 = auto: 1 under --test, 64 "
                        "otherwise)")
    p.add_argument("--peak_flops", type=float, default=0.0,
                   help="peak FLOP/s of one accelerator for the MFU "
                        "accounting in `utilization` telemetry events; "
                        "0 = per-device_kind table "
                        "(telemetry/utilization.py)")
    p.add_argument("--peak_hbm_gbps", type=float, default=0.0,
                   help="peak HBM bandwidth (GB/s) of one accelerator "
                        "for the roofline attribution in `utilization` "
                        "telemetry events; 0 = per-device_kind table "
                        "(telemetry/utilization.py)")
    p.add_argument("--no_signals", dest="signals", action="store_false",
                   default=True,
                   help="drop the per-round compression-signal health "
                        "diagnostics from the jitted round step")
    p.add_argument("--no_client_stats", dest="client_stats",
                   action="store_false", default=True,
                   help="drop the per-client population statistics "
                        "(quantile summaries + participation ledger) "
                        "from the jitted round step")
    p.add_argument("--population_sketch", choices=("auto", "on", "off"),
                   default="auto",
                   help="participation-ledger backing (telemetry/"
                        "population.py): on = bounded-memory streaming "
                        "sketches (<= 8 MiB at any population size, "
                        "fields marked estimated), off = exact per-"
                        "client dict (O(population) memory), auto = "
                        "exact below 1e5 registered clients, sketch "
                        "at/above")
    p.add_argument("--alert_action", choices=ALERT_ACTIONS, default="log",
                   help="anomaly-monitor action on a fired rule: log = "
                        "alert event only; warn = + stderr; checkpoint = "
                        "+ one-shot flight-recorder bundle (state "
                        "snapshot, last-N events, alert context); abort "
                        "= + stop training")
    p.add_argument("--alert_window", type=int, default=32,
                   help="rolling median/MAD history length (and per-rule "
                        "refire cooldown) for the anomaly monitor")
    p.add_argument("--alert_zscore", type=float, default=6.0,
                   help="robust z-score threshold for the monitor's "
                        "statistical rules")
    p.add_argument("--signals_exact", action="store_true",
                   help="compute topk_overlap (heavy-hitter recovery vs "
                        "the exact dense error top-k); adds an O(d) "
                        "top-k per round, and a dense shadow error "
                        "accumulator for table-state sketch")
    p.add_argument("--signal_groups", choices=("coarse", "leaf", "off"),
                   default="coarse",
                   help="layer-wise compression attribution "
                        "(telemetry/layer_signals.py): parameter-group "
                        "granularity of the per-group recovery signals "
                        "emitted as layer_signals events — coarse = "
                        "path-pattern groups (per-block attn/mlp/"
                        "norm-bias, embed, head; stage-level for conv "
                        "nets), leaf = one group per pytree leaf, off = "
                        "compiled out of the round entirely")
    p.add_argument("--strict_regimes", action="store_true",
                   help="fail at startup (instead of warning) on "
                        "configurations measured divergent in round 5 "
                        "(see core/server.py check_regime_health)")
    p.add_argument("--compile_cache", "--compilation_cache_dir",
                   dest="compilation_cache_dir", type=str,
                   default="~/.cache/commefficient_tpu_xla",
                   help="persistent XLA compile cache DIR; empty disables "
                        "(warm starts skip the multi-minute round compile)")
    p.add_argument("--no_pipeline", dest="pipeline", action="store_false",
                   default=True,
                   help="disable the round input pipeline (inline "
                        "fetch->dispatch; bit-identical losses, no "
                        "prefetch overlap)")
    p.add_argument("--prefetch_depth", type=int, default=2,
                   help="rounds the input pipeline prefetches ahead "
                        "(2 = double-buffered; must be >= 1 with the "
                        "pipeline enabled)")
    p.add_argument("--async_agg", action="store_true",
                   help="FedBuff-style async buffered aggregation "
                        "(core/async_agg.py): keep --max_inflight cohorts "
                        "in flight, merge landed cohort sums with "
                        "--staleness_discount weighting, commit the "
                        "buffer through the server momentum+EF step every "
                        "--buffer_goal cohorts")
    p.add_argument("--max_inflight", type=int, default=4,
                   help="cohort computations kept in flight (K); each "
                        "holds one transmitted-space array on device")
    p.add_argument("--buffer_goal", type=int, default=1,
                   help="cohorts merged per server commit (M); 1 commits "
                        "every landing cohort")
    p.add_argument("--staleness_discount",
                   choices=("none", "poly", "exp"), default="poly",
                   help="merge weight for a cohort s commits stale: none "
                        "= 1, poly = (1+s)^-alpha, exp = exp(-alpha*s)")
    p.add_argument("--staleness_alpha", type=float, default=0.5,
                   help="staleness discount exponent/rate (poly 0.5 = "
                        "FedBuff's 1/sqrt(1+s))")
    p.add_argument("--scenario",
                   choices=("none", "uniform", "lognormal", "stragglers"),
                   default="none",
                   help="straggler scenario engine (data/scenarios.py): "
                        "per-cohort simulated latency distribution; "
                        "requires --async_agg")
    p.add_argument("--scenario_latency", type=float, default=1.0,
                   help="base cohort latency, in dispatch ticks")
    p.add_argument("--scenario_spread", type=float, default=0.5,
                   help="latency spread (uniform half-width / lognormal "
                        "sigma)")
    p.add_argument("--scenario_straggler_frac", type=float, default=0.1,
                   help="'stragglers' kind: fraction of cohorts that are "
                        "slow")
    p.add_argument("--scenario_straggler_mult", type=float, default=10.0,
                   help="'stragglers' kind: latency multiplier of the "
                        "slow cohorts")
    p.add_argument("--scenario_dropout", type=float, default=0.0,
                   help="per-cohort probability of never landing (the "
                        "compute is skipped; nothing merges)")
    p.add_argument("--scenario_participation", type=float, default=1.0,
                   help="fraction of the round's worker slots that "
                        "actually participate (the rest are masked out "
                        "per cohort, deterministically)")
    p.add_argument("--adversary", choices=ADVERSARY_KINDS, default="none",
                   help="adversarial client injection: a deterministic "
                        "--adversary_frac of the client universe (keyed "
                        "off (seed, client_id)) label-flips, sign-flips, "
                        "boosts, noises or NaN-poisons its uploads; works "
                        "in sync and async rounds")
    p.add_argument("--adversary_frac", type=float, default=0.0,
                   help="fraction of the client universe that is "
                        "adversarial (required > 0 with --adversary)")
    p.add_argument("--adversary_scale", type=float, default=10.0,
                   help="scale-attack multiplier / noise-attack sigma")
    p.add_argument("--defense", choices=DEFENSES, default="none",
                   help="robust aggregation in transmitted space: "
                        "normclip = per-client update-norm clip to a "
                        "rolling-median x --defense_clip_mult threshold; "
                        "trim = per-coordinate trimmed-mean (single "
                        "device)")
    p.add_argument("--defense_clip_mult", type=float, default=3.0,
                   help="normclip threshold = rolling median per-datum "
                        "update norm x this multiplier")
    p.add_argument("--defense_window", type=int, default=8,
                   help="rounds of per-round median norms kept for the "
                        "normclip rolling-median reference")
    p.add_argument("--defense_trim_frac", type=float, default=0.1,
                   help="trim: per-coordinate fraction of clients dropped "
                        "at EACH extreme before averaging (in [0, 0.5))")
    p.add_argument("--nonfinite_action", choices=NONFINITE_ACTIONS,
                   default="abort",
                   help="nonfinite per-client update: abort = the "
                        "pre-existing all-or-nothing NaN abort; "
                        "quarantine = zero the client out of the "
                        "aggregate, bench it --quarantine_backoff rounds, "
                        "eject after --quarantine_strikes strikes (a "
                        "fully-nonfinite round still aborts)")
    p.add_argument("--quarantine_backoff", type=int, default=8,
                   help="rounds a struck client sits out before a retry")
    p.add_argument("--quarantine_strikes", type=int, default=3,
                   help="strikes before permanent ejection")
    p.add_argument("--preempt_grace", type=float, default=30.0,
                   help="graceful-preemption drain budget in seconds: "
                        "on SIGTERM/SIGINT, drain the pipeline/async "
                        "pool, write a `preempt`-tagged checkpoint "
                        "(round-granular meta) and exit 0 within this "
                        "budget; a second signal force-exits")
    p.add_argument("--watchdog", action="store_true",
                   help="arm the hang watchdog: a host thread deadlines "
                        "each round at --watchdog_mult x the rolling "
                        "median round time, fires a critical "
                        "round_stall alert + events-only flight-"
                        "recorder bundle on expiry, and wraps the "
                        "retryable input phases (device_put/gather "
                        "dispatch) in bounded exponential-backoff "
                        "retries")
    p.add_argument("--watchdog_mult", type=float, default=10.0,
                   help="stall deadline multiplier over the rolling "
                        "median round time (>= 1)")
    p.add_argument("--logdir", type=str, default="",
                   help="fixed run directory for telemetry/tensorboard "
                        "(empty = timestamped); a resumed run pointed "
                        "at its predecessor's logdir APPENDS to the "
                        "telemetry stream with a resume lineage record")
    p.add_argument("--remat", action="store_true", dest="do_remat")
    p.add_argument("--remat_policy", type=str, default="")
    p.add_argument("--lm_chunk", type=int, default=0)
    p.add_argument("--attn_impl", choices=("auto", "dense", "flash"),
                   default="auto",
                   help="GPT-2 attention: auto = dense below S=1024, "
                        "flash above (measured crossover)")
    p.add_argument("--no_fused_clients", dest="fused_clients",
                   action="store_false", default=True)
    p.add_argument("--sketch_fused_encode", choices=("auto", "on", "off"),
                   default="auto",
                   help="encode each per-microbatch gradient into the "
                        "sketch table inside the microbatch scan (table "
                        "carry; the dense (d,) gradient sum never hits "
                        "HBM): auto = when sound, on = require (fail "
                        "fast otherwise), off = the pre-fusion round")
    p.add_argument("--decode_overlap", action="store_true",
                   help="split the round into client and server-decode "
                        "executables so the PS decode of round t runs "
                        "while round t+1's client block is staged "
                        "(bit-identical losses; same soundness "
                        "constraints as --async_agg)")
    p.add_argument("--sketch_sharded_server", choices=("auto", "on", "off"),
                   default="auto",
                   help="shard the sketch server tail over the mesh "
                        "(reduce-scattered table, shard-local range "
                        "decode + candidate top-k merge; no device ever "
                        "holds the dense (d,) estimates): auto = on an "
                        "eligible mesh, on = require (fail fast "
                        "otherwise), off = the replicated tail")
    p.add_argument("--sketch_dense_clip", action="store_true",
                   help="clip the dense worker gradient before sketch "
                        "encode (threshold x num_iters) instead of the "
                        "reference's post-encode table clip")
    return parser


def parse_args(argv=None, default_lr: Optional[float] = None) -> FedConfig:
    parser = argparse.ArgumentParser()
    add_args(parser, default_lr=default_lr)
    ns = parser.parse_args(argv)
    kw = vars(ns)
    mesh_shape = tuple(int(x) for x in kw.pop("mesh_shape").split(",") if x)
    mesh_axes = tuple(x for x in kw.pop("mesh_axes").split(",") if x)
    if kw.get("sketch_dtype") is not None:
        # deprecated alias (ISSUE 14): --sketch_dtype keeps working but
        # warns once at parse time; an explicit --wire_dtype wins
        import sys
        print("WARNING: --sketch_dtype is a deprecated alias of "
              "--wire_dtype (it now also covers the int8 quantized "
              "wire); update the invocation.", file=sys.stderr)
        if not kw.get("wire_dtype"):
            kw["wire_dtype"] = kw["sketch_dtype"]
    else:
        kw["sketch_dtype"] = "float32"
    return FedConfig(mesh_shape=mesh_shape, mesh_axes=mesh_axes, **kw)
