"""Loss builders satisfying the core.client loss contract.

The reference passes ``compute_loss_train`` / ``compute_loss_val`` closures
into ``FedModel`` (cv_train.py:67-83, 389); here the equivalent closures map
``(params_pytree, batch_dict, mask) -> (mean_loss, (metrics...))`` with masked
means, and own the mixed-precision policy: parameters are cast to
``compute_dtype`` (bfloat16 by default — the MXU-native dtype) for the
forward/backward while the federated vector and all server state stay fp32.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _cast(tree, dtype):
    return jax.tree.map(
        lambda t: t.astype(dtype) if jnp.issubdtype(t.dtype, jnp.floating)
        else t, tree)


def _chunked_lm_nll(hidden, wte, labels, m, chunk):
    """Shifted LM cross-entropy without ever materializing the full
    (tokens, vocab) logits: scan the sequence in ``chunk``-token slices,
    projecting + log-softmaxing each slice and accumulating the masked
    NLL sums. ``jax.checkpoint`` on the scan body makes the backward pass
    recompute each slice's logits instead of saving them, so peak memory
    is O(chunk·V) — the enabler for microbatch ≥ 8 at the 32k-token GPT-2
    round (the full fp32 logits + cotangent were ~1.6 GB per microbatch
    step). fp32 accumulation; bitwise-equivalent math to the dense path
    up to sum reordering (asserted by tests/test_models.py)."""
    h = hidden[..., :-1, :]                           # (B, C, S-1, E)
    lab = labels[..., 1:]                             # (B, C, S-1)
    B, C, T, E = h.shape
    pad = (-T) % chunk
    if pad:
        h = jnp.pad(h, ((0, 0), (0, 0), (0, pad), (0, 0)))
        lab = jnp.pad(lab, ((0, 0), (0, 0), (0, pad)), constant_values=-100)
    nch = (T + pad) // chunk
    h = h.reshape(B, C, nch, chunk, E).transpose(2, 0, 1, 3, 4)
    lab = lab.reshape(B, C, nch, chunk).transpose(2, 0, 1, 3)

    def body(carry, inp):
        num, den = carry
        hc, lc = inp                                  # (B, C, chunk, ...)
        tok_valid = ((lc != -100) * m[:, None, None]).astype(jnp.float32)
        logits = (hc @ wte.T.astype(hc.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(
            logp, jnp.maximum(lc, 0)[..., None], axis=-1)[..., 0]
        return (num + (nll * tok_valid).sum(),
                den + tok_valid.sum()), None

    (num, den), _ = lax.scan(jax.checkpoint(body),
                             (jnp.zeros(()), jnp.zeros(())), (h, lab))
    return num / jnp.maximum(den, 1.0)


def _gpt2_losses(model, params, batch, mask, seq_axis=None, seq_shards=1,
                 lm_chunk: int = 0):
    """Shared DoubleHeads forward: (lm_nll_per_token, mc_loss, mc_acc).

    ``seq_axis``: set when the model runs seq-sharded inside a shard_map
    (ring attention). The next-token label shift then crosses shard
    boundaries — each shard fetches its right neighbour's first label
    column via ``ppermute`` — and the masked token means psum over the
    axis, so every shard computes the identical GLOBAL loss (its gradient
    contribution stays local to its tokens; the runtime sums shards).

    ``lm_chunk`` > 0 (dense path only): compute the LM loss via
    _chunked_lm_nll instead of full-vocab logits."""
    if lm_chunk > 0 and seq_axis is not None:
        # fail fast: silently falling back to full-vocab logits would OOM
        # exactly the runs that asked for the memory-bounded path
        raise ValueError(
            "lm_chunk is not supported together with a seq mesh axis yet "
            "(the seq branch computes its own cross-shard label shift on "
            "full logits); drop --lm_chunk or the seq axis")
    m = mask.astype(jnp.float32)                      # (B,)
    if lm_chunk > 0 and seq_axis is None:
        hidden, wte, mc_logits = model.apply(
            params, batch["input_ids"], batch["mc_token_ids"],
            batch["token_type_ids"], method="hidden_and_mc")
        lm_loss = _chunked_lm_nll(hidden, wte, batch["lm_labels"], m,
                                  lm_chunk)
        return (lm_loss,) + _mc_metrics(mc_logits, batch, m)

    lm_logits, mc_logits = model.apply(
        params, batch["input_ids"], batch["mc_token_ids"],
        batch["token_type_ids"])

    if seq_axis is None:
        sh_logits = lm_logits[..., :-1, :]            # (B, C, S-1, V)
        sh_labels = batch["lm_labels"][..., 1:]       # (B, C, S-1)
    else:
        # label for local position t is labels[t+1]; the last local
        # position needs the NEXT shard's first label (the global last
        # shard has no successor -> -100)
        labels = batch["lm_labels"]
        perm = [(i, (i - 1) % seq_shards) for i in range(seq_shards)]
        nxt = lax.ppermute(labels[..., :1], seq_axis, perm)
        is_last = lax.axis_index(seq_axis) == seq_shards - 1
        nxt = jnp.where(is_last, -100, nxt)
        sh_logits = lm_logits                         # (B, C, S_loc, V)
        sh_labels = jnp.concatenate([labels[..., 1:], nxt], axis=-1)
    tok_valid = ((sh_labels != -100)
                 * m[:, None, None]).astype(jnp.float32)
    safe_labels = jnp.maximum(sh_labels, 0)
    logp = jax.nn.log_softmax(sh_logits)
    tok_nll = -jnp.take_along_axis(
        logp, safe_labels[..., None], axis=-1)[..., 0]
    num, den = (tok_nll * tok_valid).sum(), tok_valid.sum()
    if seq_axis is not None:
        num = lax.psum(num, seq_axis)
        den = lax.psum(den, seq_axis)
    lm_loss = num / jnp.maximum(den, 1.0)
    return (lm_loss,) + _mc_metrics(mc_logits, batch, m)


def _mc_metrics(mc_logits, batch, m):
    mc_logp = jax.nn.log_softmax(mc_logits, axis=-1)  # (B, C)
    mc_nll = -jnp.take_along_axis(
        mc_logp, batch["mc_label"][:, None], axis=-1)[:, 0]
    denom = jnp.maximum(m.sum(), 1.0)
    mc_loss = (mc_nll * m).sum() / denom
    acc = (((jnp.argmax(mc_logits, -1) == batch["mc_label"]) * m).sum()
           / denom)
    return mc_loss, acc


def make_gpt2_train_loss(model, lm_coef: float = 1.0, mc_coef: float = 1.0,
                         seq_axis=None, seq_shards: int = 1,
                         lm_chunk: int = 0):
    """DoubleHeads training loss (reference gpt2_train.py:88-99):
    ``lm_coef * lm_loss + mc_coef * mc_loss`` where the LM loss is shifted
    cross-entropy over the gold candidate's reply tokens and the MC loss is
    cross-entropy over candidates. Metrics: (mc accuracy,). Pass
    ``seq_axis``/``seq_shards`` matching the model's when it runs
    seq-sharded; ``lm_chunk`` > 0 enables the memory-bounded chunked LM
    cross-entropy (dense path)."""

    def loss_fn(params, batch, mask):
        lm_loss, mc_loss, acc = _gpt2_losses(
            model, params, batch, mask, seq_axis=seq_axis,
            seq_shards=seq_shards, lm_chunk=lm_chunk)
        return lm_coef * lm_loss + mc_coef * mc_loss, (acc,)

    return loss_fn


def make_gpt2_val_loss(model, seq_axis=None, seq_shards: int = 1,
                       lm_chunk: int = 0):
    """Validation metrics (reference test_gpt2, gpt2_train.py:55-86):
    per-token LM NLL (=> ppl on the host) and MC accuracy."""

    def loss_fn(params, batch, mask):
        lm_loss, _, acc = _gpt2_losses(
            model, params, batch, mask, seq_axis=seq_axis,
            seq_shards=seq_shards, lm_chunk=lm_chunk)
        return lm_loss, (acc,)

    return loss_fn


def make_cv_loss(model, compute_dtype: str = "bfloat16",
                 frozen_params=None) -> Callable:
    """Masked softmax cross-entropy + top-1 accuracy (reference
    compute_loss_train/val, cv_train.py:67-83).

    ``frozen_params``: optional pytree of non-trained parameters (finetune
    mode — the reference shrinks the federated vector to just the trainable
    head, cv_train.py:377-384); merged under the trained params at apply time.
    """
    dtype = jnp.dtype(compute_dtype)

    def loss_fn(params, batch, mask) -> Tuple[jax.Array, Tuple[jax.Array]]:
        if frozen_params is not None:
            params = {"params": {**frozen_params["params"],
                                 **params["params"]}}
        x = batch["image"].astype(dtype)
        logits = model.apply(_cast(params, dtype), x).astype(jnp.float32)
        labels = batch["target"]
        logp = jax.nn.log_softmax(logits)
        ce = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        loss = (ce * m).sum() / denom
        acc = ((jnp.argmax(logits, axis=1) == labels) * m).sum() / denom
        return loss, (acc,)

    return loss_fn
