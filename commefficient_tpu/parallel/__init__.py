from commefficient_tpu.parallel.mesh import (
    FedShardings,
    init_distributed,
    make_mesh,
)
from commefficient_tpu.parallel.ring import (
    make_ring_attention,
    ring_attention_inner,
)

__all__ = [
    "FedShardings",
    "init_distributed",
    "make_mesh",
    "make_ring_attention",
    "ring_attention_inner",
]
