"""Device mesh construction and the framework's sharding layout.

This is the TPU-native replacement for the reference's entire distributed
stack (SURVEY.md §2.8): the PS + per-GPU-worker process split, the
multiprocessing queues, /dev/shm tensors and the NCCL ``reduce`` all
collapse into sharding annotations on ONE jitted program. XLA inserts the
ICI collectives (psum for the client-gradient sum, all-gathers around the
top-k) exactly where the reference hand-placed NCCL calls
(fed_worker.py:138, fed_aggregator.py:329).

Layout (single mesh axis, default name "clients"):
- the round's client axis (leading dim of batch/client_ids/mask and of the
  per-client persistent state arrays) is sharded over the axis — each device
  simulates ``num_workers / n_devices`` clients, the TPU analogue of the
  reference's one-GPU-per-worker-process;
- the dense (d,) federated vectors (ps_weights, Vvelocity, Verror, updates)
  are sharded over the same axis — server math is elementwise, so it
  partitions perfectly; XLA all-gathers only where globality is required
  (``lax.top_k``);
- count-sketch tables (r, c) shard their column axis;
- scalars and PRNG keys replicate.

Multi-host: ``init_distributed`` wraps ``jax.distributed.initialize`` — the
DCN equivalent of the reference's (vestigial, 127.0.0.1-hardcoded) NCCL
world bring-up (fed_aggregator.py:161-164).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(mesh_shape: Tuple[int, ...] = (),
              mesh_axes: Tuple[str, ...] = ("clients",),
              devices=None) -> Optional[Mesh]:
    """Build a Mesh from config. Empty ``mesh_shape`` with one device =>
    None (plain single-device jit); empty shape with several devices =>
    1-D mesh over all of them."""
    devices = devices if devices is not None else jax.devices()
    if not mesh_shape:
        if len(devices) == 1:
            return None
        mesh_shape = (len(devices),)
        mesh_axes = mesh_axes[:1]
    n = int(np.prod(mesh_shape))
    if n > len(devices):
        raise ValueError(
            f"mesh {mesh_shape} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(mesh_shape)
    return Mesh(arr, mesh_axes[:arr.ndim])


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (call once per host before building the mesh)."""
    kw = {}
    if coordinator_address is not None:
        kw = dict(coordinator_address=coordinator_address,
                  num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kw)


class FedShardings:
    """NamedShardings for every array family in a federated run."""

    def __init__(self, mesh: Mesh, axis: Optional[str] = None):
        self.mesh = mesh
        self.axis = axis if axis is not None else mesh.axis_names[0]

    def _ns(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return self._ns()

    @property
    def dense_vec(self) -> NamedSharding:           # (d,)
        # dense federated vectors shard over ALL mesh axes (on a 2-D
        # ("clients","seq") mesh every device holds d/mesh.size), so the
        # server's elementwise math uses the full machine
        return self._ns(tuple(self.mesh.axis_names))

    @property
    def sketch_table(self) -> NamedSharding:        # (r, c)
        return self._ns(None, self.axis)

    @property
    def client_rows(self) -> NamedSharding:         # (num_clients, ...)
        return self._ns(self.axis)

    @property
    def round_axis(self) -> NamedSharding:          # (num_workers, ...)
        return self._ns(self.axis)

    def transmitted(self, transmitted_shape) -> NamedSharding:
        return (self.sketch_table if len(transmitted_shape) == 2
                else self.dense_vec)

    def for_state(self, cfg, state_like) -> "jax.tree_util.PyTreeDef":
        """Sharding pytree matching a FedState.

        Weight-dimension sharding of the dense (d,) vectors and the sketch
        column axis is applied only when the dim divides the device count —
        otherwise those leaves replicate (which is exactly the reference's
        layout: every process holds the full weight vector,
        fed_aggregator.py:94-97). The runtime pads both num_clients and the
        dense length up to mesh multiples, so in practice everything
        shards."""
        n = self.mesh.shape[self.axis]
        n_dense = self.mesh.size

        # the column-sharded home layout applies exactly when the runtime's
        # round program expects it (FedRuntime._rows_cols): dense-row modes
        # with per-client velocity/error rows. Deciding here by shape alone
        # could disagree with the round's shard_map in_specs (forcing a
        # hidden W·d reshard every round), so both sides derive the
        # predicate from cfg.
        rows_cols = (cfg.mode not in ("sketch", "fedavg")
                     and (cfg.needs_client_velocities
                          or cfg.needs_client_errors))

        def leaf(path, like):
            name = path[0].name
            if name in ("client_velocities", "client_errors"):
                # dense per-client rows store COLUMN-sharded (each device
                # owns a d_row_pad/n slice of EVERY client's row): the
                # round's row gather/scatter by client_ids is then fully
                # local, and the compute<->home layout change is one
                # all_to_all of W·d/n elements — replacing the W·d
                # all-reduce pair the row-sharded layout provoked. (The
                # TPU analogue of the reference's zero-traffic /dev/shm
                # rows, fed_aggregator.py:119-129.) Sketch-mode rows are
                # (r, c) tables (already ≪ d): keep them row-sharded.
                if rows_cols:
                    assert like.ndim == 2 and like.shape[1] % n == 0, (
                        f"{name}: home layout needs a (clients, d_row_pad) "
                        f"row with n | d_row_pad, got {like.shape}")
                    return self._ns(None, self.axis)
                return self.client_rows
            if name in ("client_weights", "client_last_round"):
                return self.client_rows
            if name in ("ps_weights", "coord_last_update", "Vvelocity",
                        "Verror", "async_buffer"):
                # async_buffer shards exactly like Vvelocity: it holds
                # the same transmitted-space quantity (core/async_agg.py)
                if like.ndim == 2:       # sketch table (r, c)
                    return (self.sketch_table if like.shape[1] % n == 0
                            else self.replicated)
                return (self.dense_vec if like.shape[0] % n_dense == 0
                        else self.replicated)
            return self.replicated  # step, rng
        return jax.tree_util.tree_map_with_path(leaf, state_like)

    def divisible(self, n: int) -> bool:
        return n % self.mesh.shape[self.axis] == 0
