"""Ring attention: causal attention with the sequence axis sharded over the
device mesh.

NEW SCOPE beyond the reference, which has no long-context machinery at all
(SURVEY.md §5: max sequence = a padded PersonaChat batch, no ring/Ulysses/
blockwise anywhere). Required here because long-context is first-class for
this framework: with ``seq`` sharded over N devices each chip holds S/N
tokens, K/V blocks rotate around the ring via ``lax.ppermute`` (one ICI hop
per step, compute overlaps the N-1 hops), and softmax is accumulated online
(flash-attention style: running max ``m``, normalizer ``l``, weighted sum
``o``) so the full S x S score matrix never materializes.

Numerics: fp32 accumulators regardless of input dtype; causality enforced
from *global* token positions, so the result equals dense causal attention
exactly (see tests/test_ring.py).

Surfaces:
- ``ring_attention_inner(q, k, v, axis_name, num_shards)`` — call inside an
  existing ``shard_map``/pjit; q,k,v are the local (..., S/N, H, D) shards.
- ``make_ring_attention(mesh, axis)`` — standalone wrapper returning a
  drop-in ``attn_impl`` for ``models.gpt2`` modules: full (..., S, H, D)
  arrays in/out, shard_map applied internally.
"""

from __future__ import annotations

import functools
import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG = -1e30


def ring_attention_inner(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, num_shards: int) -> jax.Array:
    """Causal ring attention on per-device shards.

    q, k, v: (..., Sl, H, D) local blocks (Sl = S / num_shards, in ring
    order: shard i holds global positions [i*Sl, (i+1)*Sl)).
    Returns the local (..., Sl, H, D) attention output.
    """
    Sl, H, D = q.shape[-3:]
    scale = 1.0 / math.sqrt(D)
    my = lax.axis_index(axis_name)
    qpos = my * Sl + jnp.arange(Sl)                       # global q positions
    qf = q.astype(jnp.float32)

    batch_shape = q.shape[:-3]
    # accumulators start identical on every device but become
    # device-varying after the first step — mark them varying up front
    # (shard_map's check would otherwise reject the scan carry)
    from commefficient_tpu.utils.jax_compat import pcast
    m0, l0, o0 = jax.tree.map(
        lambda t: pcast(t, (axis_name,), to="varying"),
        (jnp.full(batch_shape + (H, Sl), NEG, jnp.float32),
         jnp.zeros(batch_shape + (H, Sl), jnp.float32),
         jnp.zeros(batch_shape + (Sl, H, D), jnp.float32)))

    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    def step(carry, _):
        k_blk, v_blk, src, m, l, o = carry
        logits = jnp.einsum("...qhd,...khd->...hqk", qf,
                            k_blk.astype(jnp.float32)) * scale
        kpos = src * Sl + jnp.arange(Sl)                  # global k positions
        causal = qpos[:, None] >= kpos[None, :]           # (Sl, Sl)
        logits = jnp.where(causal, logits, NEG)

        blk_max = logits.max(axis=-1)                     # (..., H, Sl)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(logits - m_new[..., None])            # (..., H, Sl, Sl)
        p = jnp.where(causal, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("...hqk,...khd->...qhd", p,
                        v_blk.astype(jnp.float32))
        o = o * jnp.moveaxis(corr, -2, -1)[..., None] + pv
        m = m_new

        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (k_blk, v_blk, src, m, l, o), None

    init = (k, v, my, m0, l0, o0)
    (_, _, _, m, l, o), _ = lax.scan(step, init, None, length=num_shards)
    denom = jnp.maximum(jnp.moveaxis(l, -2, -1), 1e-30)[..., None]
    return (o / denom).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "seq") -> Callable:
    """Drop-in ``attn_impl`` for the GPT-2 modules: takes full
    (..., S, H, D) arrays, shards S over ``axis`` and runs the ring."""
    from commefficient_tpu.utils.jax_compat import shard_map

    n = mesh.shape[axis]

    def attn(q, k, v):
        nd = q.ndim
        # build a PartitionSpec placing `axis` at dim -3
        ax_spec = P(*([None] * (nd - 3) + [axis, None, None]))
        inner = functools.partial(ring_attention_inner, axis_name=axis,
                                  num_shards=n)
        return shard_map(inner, mesh=mesh,
                         in_specs=(ax_spec, ax_spec, ax_spec),
                         out_specs=ax_spec)(q, k, v)

    return attn
