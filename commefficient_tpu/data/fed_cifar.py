"""Federated CIFAR-10/100: one natural client per class.

Parity target: reference ``FedCIFAR10``/``FedCIFAR100``
(CommEfficient/data_utils/fed_cifar.py:13-100): ``prepare_datasets`` splits
the train set by label into per-client ``client{i}.npy`` files plus a
``test.npz`` and ``stats.json``; the train *target* of every item equals its
natural client id (class). We keep the identical on-disk layout (a dataset
prepared by the reference loads here unchanged) but read it into flat packed
arrays once.

Source material: the reference uses torchvision's downloader; this
environment has no torchvision and no network, so ``prepare_datasets``
consumes the standard CIFAR python pickle directories
(``cifar-10-batches-py`` / ``cifar-100-python``) if present in
``dataset_dir``, and otherwise (``synthetic=True``) generates a small
deterministic class-structured synthetic set so every pipeline stage stays
exercisable end-to-end.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

# version tag for the synthetic generator's semantics; "shared-v3" =
# train/val share class prototypes (val differs only in noise) and the
# EASY branch's prototypes are low-frequency (coarse 8x8 upsampled —
# see _synthetic_cifar) so downsampling stems can learn them
_SYNTH_PROTOS = "shared-v3"

# hard-regime knobs (see _synthetic_cifar hard=True), calibrated by TPU
# sweeps so a 24-epoch run lands below 100% val accuracy EVEN
# UNCOMPRESSED (round-4 calibration: uncompressed ResNet-9 reaches
# 90.9% at epoch 24 — a nontrivial ceiling near the reference
# lineage's 94% real-CIFAR target, so compression gaps are measured
# against real headroom; the round-3 constants 0.15/60/70 let
# uncompressed and true_topk saturate at 100% by epochs 11/13). The
# class evidence is SPARSE (a _HARD_FRAC subset of pixels carries a
# strong ±_HARD_DELTA offset): gradients then have heavy hitters, the
# structure FetchSGD-style top-k/sketch methods target. (A first,
# uniform-evidence design — every pixel carrying a faint delta — was
# measured top-k-ADVERSARIAL: uncompressed reached 95% while
# sketch/top-k stalled at ~20%, because no coordinate mattered more
# than any other and only k/d of a uniformly-informative gradient
# survives sparsification.)
_HARD_FRAC = 0.10
_HARD_DELTA = 45
_HARD_NOISE = 85


def _synthetic_cifar(num_classes: int, per_class: int, img_hw: int = 32,
                     seed: int = 1234, proto_seed: int = 777,
                     hard: bool = False, label_noise: float = 0.0):
    """Class-structured gaussian images: each class has a distinct mean
    pattern so that models can actually fit the data in tests.

    The class prototypes come from ``proto_seed`` (FIXED by default) while
    per-image noise comes from ``seed`` — so a train split (seed A) and a
    val split (seed B) describe the SAME classes with fresh noise, making
    validation accuracy a real generalization measure instead of an
    unlearnable-by-construction one.

    ``hard=True`` is the NON-SATURATING regime for time-to-accuracy
    studies (VERDICT r2: the default prototypes are near-separable and a
    24-epoch curve pins at 100% by epoch 5, carrying no information about
    optimization quality): every class shares one base pattern and
    differs only by a low-amplitude delta (SNR well under the per-image
    noise), so class evidence is spread thin across all pixels and a
    capacity-limited model climbs slowly; ``label_noise`` additionally
    re-draws that fraction of labels uniformly (train-split only by
    convention — callers keep val labels clean so accuracy measures the
    true classes)."""
    prng = np.random.RandomState(proto_seed)
    if hard:
        # base in the mid-range so delta+noise rarely clip (clipping at
        # 0/255 would destroy the class signal); sparse heavy-tailed
        # class evidence — see the _HARD_* constants' rationale
        base = prng.randint(70, 185, size=(1, img_hw, img_hw, 3))
        where = prng.rand(num_classes, img_hw, img_hw, 1) < _HARD_FRAC
        signs = prng.choice([-1, 1],
                            size=(num_classes, img_hw, img_hw, 3))
        protos = np.clip(base + where * signs * _HARD_DELTA, 0, 255)
        noise_amp = _HARD_NOISE
    else:
        # LOW-FREQUENCY prototypes (coarse 8x8 patterns upsampled to
        # img_hw): class evidence that survives downsampling stems.
        # iid-per-pixel prototypes (the shared-v2 design) are destroyed
        # by any stride-2 7x7 stem — a torchvision resnet50 measured
        # train-acc 54% / val-acc chance on them (pure high-frequency
        # memorization), while the same run on low-frequency prototypes
        # generalizes. Natural images are low-frequency-dominated, so
        # this is also the more faithful synthetic stand-in.
        coarse = prng.randint(0, 255, size=(num_classes, 8, 8, 3))
        reps = -(-img_hw // 8)      # ceil: cover img_hw, then trim
        protos = np.kron(coarse, np.ones((1, reps, reps, 1), int))
        protos = protos[:, :img_hw, :img_hw]
        noise_amp = 60
    rng = np.random.RandomState(seed)
    images, targets = [], []
    for c in range(num_classes):
        noise = rng.randint(-noise_amp, noise_amp,
                            size=(per_class, img_hw, img_hw, 3))
        imgs = np.clip(protos[c][None] + noise, 0, 255).astype(np.uint8)
        images.append(imgs)
        targets.append(np.full(per_class, c, dtype=np.int64))
    images, targets = np.concatenate(images), np.concatenate(targets)
    if label_noise > 0:
        flip = rng.rand(len(targets)) < label_noise
        targets = np.where(flip, rng.randint(0, num_classes, len(targets)),
                           targets)
    return images, targets


class FedCIFAR10(FedDataset):
    expected_natural_clients = 10

    num_classes = 10
    _pickle_dir = "cifar-10-batches-py"
    _train_files = [f"data_batch_{i}" for i in range(1, 6)]
    _test_file = "test_batch"
    _label_key = b"labels"

    def __init__(self, *args, synthetic: Optional[bool] = None,
                 synthetic_per_class: int = 64,
                 synthetic_hard: bool = False,
                 synthetic_label_noise: float = 0.0, **kw):
        # synthetic: True = force synthetic, False = require real data,
        # None = auto-fallback to synthetic (with a warning) when the raw
        # data is absent — the expected no-network verification path.
        # synthetic_hard / synthetic_label_noise: the non-saturating
        # time-to-accuracy regime (see _synthetic_cifar; label noise
        # applies to the train split only).
        self._synthetic = synthetic
        self._synthetic_per_class = synthetic_per_class
        self._synthetic_hard = synthetic_hard
        self._synthetic_label_noise = synthetic_label_noise
        # Prep-config invalidation for OUR (prefixed) prepared stats:
        # synthetic preps record their size + generator version, so
        # changing --synthetic_per_class (or a generator fix) re-prepares
        # instead of silently reusing stale arrays (shared base-class
        # policy: FedDataset._invalidate_stale_synth_prep)
        dataset_dir = args[0] if args else kw.get("dataset_dir")
        self._invalidate_stale_synth_prep(dataset_dir, synthetic)
        super().__init__(*args, **kw)

    @classmethod
    def _has_real_source(cls, dataset_dir: str) -> bool:
        return os.path.isdir(os.path.join(dataset_dir, cls._pickle_dir))

    def _synth_marker(self) -> dict:
        """Everything a synthetic prep bakes into its arrays — ANY field
        change must invalidate the cache (subclasses add their knobs)."""
        return {"per_class": self._synthetic_per_class,
                "protos": _SYNTH_PROTOS,
                # the hard marker carries the regime knobs: retuning them
                # must invalidate previously prepared arrays
                "hard": ([_HARD_FRAC, _HARD_DELTA, _HARD_NOISE]
                         if self._synthetic_hard else False),
                "label_noise": self._synthetic_label_noise}

    # --------------------------------------------------------- preparation

    def _load_pickles(self, files):
        images, labels = [], []
        for fn in files:
            with open(os.path.join(self.dataset_dir, self._pickle_dir, fn),
                      "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(d[b"data"].reshape(-1, 3, 32, 32)
                          .transpose(0, 2, 3, 1))  # -> NHWC
            labels.append(np.asarray(d[self._label_key], dtype=np.int64))
        return np.concatenate(images), np.concatenate(labels)

    def _prepare(self, download: bool = False) -> None:
        pickled = os.path.join(self.dataset_dir, self._pickle_dir)
        marker = None
        if os.path.isdir(pickled) and not self._synthetic:
            train_images, train_targets = self._load_pickles(
                self._train_files)
            test_images, test_targets = self._load_pickles([self._test_file])
        elif self._synthetic is False:
            raise FileNotFoundError(
                f"no {self._pickle_dir} under {self.dataset_dir} and "
                "synthetic=False; place the CIFAR python pickles there or "
                "pass synthetic=True")
        else:
            if self._synthetic is None:
                print(f"WARNING: no {self._pickle_dir} under "
                      f"{self.dataset_dir}; generating synthetic data")
            train_images, train_targets = _synthetic_cifar(
                self.num_classes, self._synthetic_per_class,
                hard=self._synthetic_hard,
                label_noise=self._synthetic_label_noise)
            # val: same prototypes, fresh noise, CLEAN labels (accuracy
            # must measure the true classes even under train label noise)
            test_images, test_targets = _synthetic_cifar(
                self.num_classes, max(self._synthetic_per_class // 4, 2),
                seed=4321, hard=self._synthetic_hard)
            marker = self._synth_marker()

        os.makedirs(self.dataset_dir, exist_ok=True)
        images_per_client = []
        for c in range(self.num_classes):
            sel = np.where(train_targets == c)[0]
            images_per_client.append(len(sel))
            np.save(self.client_fn(c), train_images[sel])
        np.savez(self.test_fn(), test_images=test_images,
                 test_targets=test_targets)
        self.write_stats(images_per_client, len(test_targets),
                         **({"synthetic": marker} if marker else {}))

    # ------------------------------------------------------------- loading

    def _load_arrays(self) -> None:
        if self.train:
            imgs = [np.load(self.client_fn(c))
                    for c in range(len(self.images_per_client))]
            images = np.concatenate(imgs)
            # train target == natural client id (reference fed_cifar.py:78-84)
            targets = np.repeat(np.arange(len(imgs), dtype=np.int64),
                                self.images_per_client)
        else:
            with np.load(self.test_fn()) as t:
                images = t["test_images"]
                targets = t["test_targets"].astype(np.int64)
        self.arrays = {"image": images, "target": targets}

    def client_fn(self, client_id: int) -> str:
        # class-prefixed in shared dirs; the reference's plain client{i}.npy
        # (fed_cifar.py:78-84) when the directory is a legacy layout
        # (FedDataset.data_fn policy)
        return self.data_fn(f"client{client_id}.npy")

    def test_fn(self) -> str:
        return self.data_fn("test.npz")


class FedCIFAR100(FedCIFAR10):
    expected_natural_clients = 100

    num_classes = 100
    _pickle_dir = "cifar-100-python"
    _train_files = ["train"]
    _test_file = "test"
    _label_key = b"fine_labels"
