"""Federated EMNIST (LEAF FEMNIST): 3500 natural clients (writers).

Parity target: reference ``FedEMNIST`` (CommEfficient/data_utils/
fed_emnist.py:36-138), which converts the LEAF ``all_data_*.json`` files into
per-client tensors concatenated with offsets (to dodge fd limits). Here the
one-time conversion packs everything into two npz files (train/val) holding
flat arrays sorted by client + ``stats.json`` — a layout the vectorized
``gather`` can fancy-index directly.

LEAF json schema consumed (same as the reference, fed_emnist.py:95-123):
``{"users": [...], "user_data": {user: {"x": [784-float lists], "y": [int]}}}``.
A ``synthetic=True`` fallback generates a small writer-structured set for
tests/no-data environments.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

NUM_CLASSES = 62
IMG = 28


def _synthetic_emnist(num_clients: int = 20, per_client: int = 24,
                      seed: int = 99):
    rng = np.random.RandomState(seed)
    protos = rng.rand(NUM_CLASSES, IMG, IMG).astype(np.float32)
    images, targets, per = [], [], []
    for _ in range(num_clients):
        ys = rng.randint(0, NUM_CLASSES, size=per_client)
        xs = np.clip(protos[ys] + rng.randn(per_client, IMG, IMG) * 0.1,
                     0, 1).astype(np.float32)
        images.append(xs)
        targets.append(ys.astype(np.int64))
        per.append(per_client)
    return np.concatenate(images), np.concatenate(targets), per


class FedEMNIST(FedDataset):
    def __init__(self, *args, synthetic=None, **kw):
        # True = force synthetic, False = require LEAF json, None = auto
        # fallback with a warning (zero-egress verification path)
        self._synthetic = synthetic
        super().__init__(*args, **kw)

    def _leaf_dir(self, split: str) -> str:
        return os.path.join(self.dataset_dir, split)

    def _read_leaf(self, split: str):
        files = sorted(glob.glob(
            os.path.join(self._leaf_dir(split), "all_data*.json")))
        if not files:
            return None
        images, targets, per_client = [], [], []
        for fn in files:
            with open(fn) as f:
                blob = json.load(f)
            for user in blob["users"]:
                ud = blob["user_data"][user]
                x = np.asarray(ud["x"], np.float32).reshape(-1, IMG, IMG)
                y = np.asarray(ud["y"], np.int64)
                images.append(x)
                targets.append(y)
                per_client.append(len(y))
        return np.concatenate(images), np.concatenate(targets), per_client

    def _prepare(self, download: bool = False) -> None:
        train = None if self._synthetic else self._read_leaf("train")
        val = None if self._synthetic else self._read_leaf("test")
        if train is None:
            if self._synthetic is False:
                raise FileNotFoundError(
                    f"no LEAF json under {self.dataset_dir}/train and "
                    "synthetic=False")
            if self._synthetic is None:
                print(f"WARNING: no LEAF json under {self.dataset_dir}; "
                      "generating synthetic data")
            train = _synthetic_emnist()
            vx, vy, _ = _synthetic_emnist(num_clients=4, seed=7)
            val = (vx, vy, None)
        if val is None:
            raise FileNotFoundError(
                f"LEAF train split found under {self.dataset_dir} but the "
                "test split is missing (expected test/all_data*.json)")
        os.makedirs(self.dataset_dir, exist_ok=True)
        tx, ty, per_client = train
        prefix = type(self).__name__
        np.savez(os.path.join(self.dataset_dir, f"{prefix}_train.npz"),
                 images=tx, targets=ty)
        vx, vy = val[0], val[1]
        np.savez(os.path.join(self.dataset_dir, f"{prefix}_val.npz"),
                 images=vx, targets=vy)
        self.write_stats(per_client, len(vy))

    def _load_arrays(self) -> None:
        fn = (self.data_fn("train.npz") if self.train
              else self.data_fn("val.npz"))
        with np.load(fn) as d:
            images = d["images"].astype(np.float32)
            targets = d["targets"].astype(np.int64)
        self.arrays = {"image": images[..., None],  # NHWC, 1 channel
                       "target": targets}
