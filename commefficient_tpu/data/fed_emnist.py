"""Federated EMNIST (LEAF FEMNIST): 3500 natural clients (writers).

Parity target: reference ``FedEMNIST`` (CommEfficient/data_utils/
fed_emnist.py:36-138), which converts the LEAF ``all_data_*.json`` files into
per-client tensors concatenated with offsets (to dodge fd limits). Here the
one-time conversion packs everything into two npz files (train/val) holding
flat arrays sorted by client + ``stats.json`` — a layout the vectorized
``gather`` can fancy-index directly.

LEAF json schema consumed (same as the reference, fed_emnist.py:95-123):
``{"users": [...], "user_data": {user: {"x": [784-float lists], "y": [int]}}}``.
A ``synthetic=True`` fallback generates a small writer-structured set for
tests/no-data environments.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

NUM_CLASSES = 62
IMG = 28


def _synthetic_emnist(num_clients: int = 20, per_client: int = 24,
                      seed: int = 99, proto_seed: int = 777):
    """Writer-structured synthetic set. Class PROTOTYPES come from
    ``proto_seed`` (fixed by default) while per-image noise and labels
    come from ``seed`` — so the train split (seed 99) and the val split
    (seed 7) describe the SAME classes with fresh noise, and validation
    accuracy measures generalization. (Round-4 fix: prototypes used to
    be drawn from ``seed`` too, which made the two splits' classes
    UNRELATED and pinned every synthetic-EMNIST val accuracy at chance
    by construction — the same design _synthetic_cifar already had.)"""
    prng = np.random.RandomState(proto_seed)
    protos = prng.rand(NUM_CLASSES, IMG, IMG).astype(np.float32)
    rng = np.random.RandomState(seed)
    images, targets, per = [], [], []
    for _ in range(num_clients):
        ys = rng.randint(0, NUM_CLASSES, size=per_client)
        xs = np.clip(protos[ys] + rng.randn(per_client, IMG, IMG) * 0.1,
                     0, 1).astype(np.float32)
        images.append(xs)
        targets.append(ys.astype(np.int64))
        per.append(per_client)
    return np.concatenate(images), np.concatenate(targets), per


# version tag of the synthetic generator's semantics; "shared-v1" =
# train/val share class prototypes (proto_seed) — bump on any change to
# _synthetic_emnist so stale prepared arrays re-prepare
_SYNTH_PROTOS = "shared-v1"


class FedEMNIST(FedDataset):
    def __init__(self, *args, synthetic=None, **kw):
        # True = force synthetic, False = require LEAF json, None = auto
        # fallback with a warning (zero-egress verification path)
        self._synthetic = synthetic
        # synthetic-prep invalidation: shared base-class policy (see
        # FedDataset._invalidate_stale_synth_prep — e.g. the round-4
        # prototype fix changed the arrays' semantics, and silently
        # reusing a pre-fix cache would pin val accuracy at chance)
        dataset_dir = args[0] if args else kw.get("dataset_dir")
        self._invalidate_stale_synth_prep(dataset_dir, synthetic)
        super().__init__(*args, **kw)

    @classmethod
    def _has_real_source(cls, dataset_dir: str) -> bool:
        return bool(glob.glob(
            os.path.join(dataset_dir, "train", "all_data*.json")))

    def _synth_marker(self) -> dict:
        return {"protos": _SYNTH_PROTOS}

    def _leaf_dir(self, split: str) -> str:
        return os.path.join(self.dataset_dir, split)

    def _read_leaf(self, split: str):
        files = sorted(glob.glob(
            os.path.join(self._leaf_dir(split), "all_data*.json")))
        if not files:
            return None
        images, targets, per_client = [], [], []
        for fn in files:
            with open(fn) as f:
                blob = json.load(f)
            for user in blob["users"]:
                ud = blob["user_data"][user]
                x = np.asarray(ud["x"], np.float32).reshape(-1, IMG, IMG)
                y = np.asarray(ud["y"], np.int64)
                images.append(x)
                targets.append(y)
                per_client.append(len(y))
        return np.concatenate(images), np.concatenate(targets), per_client

    def _prepare(self, download: bool = False) -> None:
        marker = None
        train = None if self._synthetic else self._read_leaf("train")
        val = None if self._synthetic else self._read_leaf("test")
        if train is None:
            if self._synthetic is False:
                raise FileNotFoundError(
                    f"no LEAF json under {self.dataset_dir}/train and "
                    "synthetic=False")
            if self._synthetic is None:
                print(f"WARNING: no LEAF json under {self.dataset_dir}; "
                      "generating synthetic data")
            train = _synthetic_emnist()
            vx, vy, _ = _synthetic_emnist(num_clients=4, seed=7)
            val = (vx, vy, None)
            marker = self._synth_marker()
        if val is None:
            raise FileNotFoundError(
                f"LEAF train split found under {self.dataset_dir} but the "
                "test split is missing (expected test/all_data*.json)")
        os.makedirs(self.dataset_dir, exist_ok=True)
        tx, ty, per_client = train
        prefix = type(self).__name__
        np.savez(os.path.join(self.dataset_dir, f"{prefix}_train.npz"),
                 images=tx, targets=ty)
        vx, vy = val[0], val[1]
        np.savez(os.path.join(self.dataset_dir, f"{prefix}_val.npz"),
                 images=vx, targets=vy)
        self.write_stats(per_client, len(vy), synthetic=marker)

    def _load_arrays(self) -> None:
        fn = (self.data_fn("train.npz") if self.train
              else self.data_fn("val.npz"))
        with np.load(fn) as d:
            images = d["images"].astype(np.float32)
            targets = d["targets"].astype(np.int64)
        self.arrays = {"image": images[..., None],  # NHWC, 1 channel
                       "target": targets}
