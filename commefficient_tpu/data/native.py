"""ctypes bindings for the native C++ data-plane (native/fedloader.cpp).

Compiles the shared library on first use with g++ (no pybind11 in this
environment; pure C ABI + ctypes). Falls back silently to the numpy
transforms when a compiler is unavailable — set
``COMMEFFICIENT_NATIVE=0`` to force the numpy path,
``COMMEFFICIENT_NATIVE=1`` to make a missing native build an error.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "fedloader.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libfedloader.so")

_lib = None
_tried = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-pthread", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("COMMEFFICIENT_NATIVE") == "0":
        return None
    if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
        if not _build():
            if os.environ.get("COMMEFFICIENT_NATIVE") == "1":
                raise RuntimeError("native fedloader build failed")
            return None
    lib = ctypes.CDLL(_SO)
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.fedloader_gather_augment.argtypes = [
        u8p, i64p, f32p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p, f32p,
        ctypes.c_uint64, ctypes.c_int]
    lib.fedloader_gather_normalize.argtypes = [
        u8p, i64p, f32p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, f32p, f32p, ctypes.c_int]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def gather_augment(images: np.ndarray, idx: np.ndarray, mean: np.ndarray,
                   std: np.ndarray, pad: int, flip: bool, seed: int,
                   num_threads: int = 0) -> np.ndarray:
    """Fused gather + crop/flip + normalize. ``images``: (N, H, W, C) uint8;
    ``idx``: any int shape; returns float32 with idx.shape + (H, W, C)."""
    lib = get_lib()
    assert lib is not None
    n_threads = num_threads or min(8, os.cpu_count() or 1)
    flat_idx = np.ascontiguousarray(idx.reshape(-1), np.int64)
    h, w, c = images.shape[1:]
    out = np.empty((flat_idx.size, h, w, c), np.float32)
    lib.fedloader_gather_augment(
        np.ascontiguousarray(images), flat_idx, out, flat_idx.size,
        h, w, c, pad, int(flip),
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        ctypes.c_uint64(seed), n_threads)
    return out.reshape(idx.shape + (h, w, c))


def gather_normalize(images: np.ndarray, idx: np.ndarray, mean: np.ndarray,
                     std: np.ndarray, num_threads: int = 0) -> np.ndarray:
    lib = get_lib()
    assert lib is not None
    n_threads = num_threads or min(8, os.cpu_count() or 1)
    flat_idx = np.ascontiguousarray(idx.reshape(-1), np.int64)
    h, w, c = images.shape[1:]
    out = np.empty((flat_idx.size, h, w, c), np.float32)
    lib.fedloader_gather_normalize(
        np.ascontiguousarray(images), flat_idx, out, flat_idx.size,
        h, w, c,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32), n_threads)
    return out.reshape(idx.shape + (h, w, c))
