"""Straggler scenario engine: deterministic per-cohort fates for async
buffered aggregation (core/async_agg.py).

A "scenario" decides, for each dispatched cohort, three things a real
federated deployment exhibits and the lockstep simulator never did:

- **latency** — how many dispatch ticks pass before the cohort's upload
  lands at the server (the AsyncAggregator merges in arrival order, so
  latency is what produces staleness);
- **dropout** — whether the cohort never lands at all (churn: the
  driver skips the compute entirely, nothing merges);
- **partial participation** — which of the round's worker slots
  actually participate (the rest are masked out, contributing no data
  but keeping the static shapes the jitted round needs).

Determinism contract: every fate derives from ``(seed, cohort_idx)``
alone — ``np.random.default_rng((seed, cohort_idx))`` — never from call
order or shared mutable RNG state, so a run replays bit-identically
across resumes, prefetch interleavings and in-flight pool sizes (the
same contract core/pipeline.py keys its augmentation randomness on).

Latency kinds:

- ``none``       — 0 ticks (no staleness; dropout/participation still
  apply);
- ``uniform``    — U[max(latency - spread, 0), latency + spread];
- ``lognormal``  — exp(N(ln latency, spread)), the classic heavy-ish
  device-speed distribution;
- ``stragglers`` — a two-point mixture: ``latency`` ticks for most
  cohorts, ``latency * straggler_mult`` for a ``straggler_frac``
  minority — the sharpest tool for staleness-discount studies.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import numpy as np

from commefficient_tpu.config import ADVERSARY_KINDS

SCENARIO_KINDS = ("none", "uniform", "lognormal", "stragglers")
# salt folded into the per-client adversary draw so it can never collide
# with the per-cohort latency/dropout stream keyed off the same seed
_ADV_SALT = 0xAD5E


class CohortFate(NamedTuple):
    """What the scenario decided for one cohort."""

    latency: float        # dispatch ticks until the upload lands
    dropped: bool         # True: the cohort never lands (skip compute)
    mask: np.ndarray      # (num_workers, B) bool, participation-reduced
    # per-slot adversarial fates (AdversaryPlan; None when no plan or no
    # client_ids were given): True marks a slot whose client is hostile.
    # Unlike latency/dropout these key off (seed, CLIENT_ID), not the
    # cohort index — the same client misbehaves every time it is sampled.
    adversary: Optional[np.ndarray] = None


class AdversaryPlan:
    """Deterministic per-client adversarial fate assignment.

    A client is adversarial iff its (seed, _ADV_SALT, client_id)-keyed
    uniform draw falls below ``frac`` — independent per client, so the
    assignment never depends on the universe size, the sampling order,
    or which other clients were asked about (the same determinism
    contract as the cohort fates above). The runtime bakes
    :meth:`universe_mask` into the jitted round as a tiny boolean
    constant; the driver uses :meth:`slot_mask` for the per-round
    injected-count telemetry — both read the SAME per-client draw.
    """

    def __init__(self, kind: str, frac: float, *, seed: int = 0,
                 scale: float = 10.0):
        if kind not in ADVERSARY_KINDS:
            raise ValueError(f"unknown adversary kind {kind!r}; "
                             f"choices: {ADVERSARY_KINDS}")
        if not 0.0 <= frac <= 1.0:
            raise ValueError(f"adversary frac must be in [0, 1], got {frac}")
        if scale <= 0:
            raise ValueError(f"adversary scale must be > 0, got {scale}")
        self.kind = kind
        self.frac = float(frac)
        self.seed = int(seed)
        self.scale = float(scale)
        # per-client draws are pure in (seed, client_id) but each costs a
        # PCG64 construction, and slot_mask runs once per dispatched
        # cohort — memoize per instance
        self._memo: dict = {}

    def is_adversary(self, client_id: int) -> bool:
        if self.kind == "none" or self.frac <= 0.0:
            return False
        cid = int(client_id)
        hit = self._memo.get(cid)
        if hit is None:
            r = np.random.default_rng(
                (self.seed, _ADV_SALT, cid)).random()
            hit = self._memo[cid] = bool(r < self.frac)
        return hit

    def slot_mask(self, client_ids) -> np.ndarray:
        """(W,) bool: which of the round's slots hold hostile clients."""
        ids = np.asarray(client_ids).reshape(-1)
        return np.fromiter((self.is_adversary(c) for c in ids),
                           dtype=bool, count=len(ids))

    def universe_mask(self, num_clients: int) -> np.ndarray:
        """(num_clients,) bool over the whole client universe."""
        return self.slot_mask(np.arange(int(num_clients)))


def make_adversary(cfg, seed: Optional[int] = None
                   ) -> Optional["AdversaryPlan"]:
    """Build the configured AdversaryPlan from a FedConfig, or None when
    injection is off."""
    if cfg.adversary == "none":
        return None
    return AdversaryPlan(cfg.adversary, cfg.adversary_frac,
                         seed=int(cfg.seed if seed is None else seed),
                         scale=cfg.adversary_scale)


class StragglerScenario:
    """Deterministic per-cohort fate generator (see module docstring)."""

    def __init__(self, kind: str = "none", *, seed: int = 0,
                 latency: float = 1.0, spread: float = 0.5,
                 straggler_frac: float = 0.1,
                 straggler_mult: float = 10.0,
                 dropout: float = 0.0, participation: float = 1.0,
                 adversary: Optional[AdversaryPlan] = None):
        if kind not in SCENARIO_KINDS:
            raise ValueError(f"unknown scenario kind {kind!r}; "
                             f"choices: {SCENARIO_KINDS}")
        if latency < 0 or spread < 0:
            raise ValueError(
                f"latency/spread must be >= 0, got latency={latency} "
                f"spread={spread}")
        if not 0.0 <= dropout < 1.0:
            raise ValueError(f"dropout must be in [0, 1), got {dropout}")
        if not 0.0 < participation <= 1.0:
            raise ValueError(
                f"participation must be in (0, 1], got {participation}")
        if not 0.0 <= straggler_frac <= 1.0:
            raise ValueError(
                f"straggler_frac must be in [0, 1], got {straggler_frac}")
        if straggler_mult < 1.0:
            # a multiplier below 1 makes the "stragglers" FASTER than the
            # rest — a silently degenerate two-point mixture that inverts
            # every staleness-study conclusion drawn from it
            raise ValueError(
                f"straggler_mult must be >= 1 (stragglers are SLOWER), "
                f"got {straggler_mult}")
        self.kind = kind
        self.seed = int(seed)
        self.latency = float(latency)
        self.spread = float(spread)
        self.straggler_frac = float(straggler_frac)
        self.straggler_mult = float(straggler_mult)
        self.dropout = float(dropout)
        self.participation = float(participation)
        self.adversary = adversary

    def _latency(self, rng: np.random.Generator) -> float:
        if self.kind == "none":
            return 0.0
        if self.kind == "uniform":
            lo = max(self.latency - self.spread, 0.0)
            return float(rng.uniform(lo, self.latency + self.spread))
        if self.kind == "lognormal":
            mu = math.log(max(self.latency, 1e-9))
            return float(rng.lognormal(mean=mu, sigma=self.spread))
        # stragglers: two-point mixture
        lat = self.latency
        if rng.random() < self.straggler_frac:
            lat *= self.straggler_mult
        return float(lat)

    def fate(self, cohort_idx: int, mask: np.ndarray,
             client_ids=None) -> CohortFate:
        """Fate of cohort ``cohort_idx`` (the global round index).

        The per-cohort draws happen in a FIXED order (latency, dropout,
        participation) from a fresh ``(seed, cohort_idx)``-keyed
        generator, so a fate never depends on which other cohorts were
        asked about. Participation only ever REMOVES slots (mask & keep)
        and always keeps at least one, so a participating cohort always
        carries data. With an :class:`AdversaryPlan` attached and
        ``client_ids`` given, the fate also carries each slot's
        adversarial assignment — keyed off the CLIENT id, never the
        cohort, so it cannot perturb (or be perturbed by) the cohort
        draw sequence above.
        """
        rng = np.random.default_rng((self.seed, int(cohort_idx)))
        latency = self._latency(rng)
        dropped = bool(rng.random() < self.dropout)
        mask = np.asarray(mask)
        out_mask = mask
        if self.participation < 1.0:
            keep = rng.random(mask.shape[0]) < self.participation
            if not keep.any():
                keep[int(rng.integers(mask.shape[0]))] = True
            out_mask = mask & keep[:, None]
        adv = (self.adversary.slot_mask(client_ids)
               if self.adversary is not None and client_ids is not None
               else None)
        return CohortFate(latency, dropped, out_mask, adv)


def make_scenario(cfg, seed: Optional[int] = None
                  ) -> Optional[StragglerScenario]:
    """Build the configured scenario from a FedConfig, or None when the
    configuration is trivial (no latency kind, no dropout, full
    participation) — the AsyncAggregator treats None as
    latency-0/no-drop, skipping the per-cohort RNG work entirely."""
    if (cfg.scenario == "none" and cfg.scenario_dropout == 0.0
            and cfg.scenario_participation >= 1.0):
        return None
    return StragglerScenario(
        cfg.scenario,
        seed=int(cfg.seed if seed is None else seed),
        latency=cfg.scenario_latency,
        spread=cfg.scenario_spread,
        straggler_frac=cfg.scenario_straggler_frac,
        straggler_mult=cfg.scenario_straggler_mult,
        dropout=cfg.scenario_dropout,
        participation=cfg.scenario_participation,
        adversary=make_adversary(cfg, seed=seed))
