"""Client-partitioned in-memory dataset base.

Re-design of the reference's ``FedDataset`` (CommEfficient/data_utils/
fed_dataset.py:9-99). The reference is a torch ``Dataset`` that maps a flat
index to (client_id, item) on every ``__getitem__`` via cumsum/searchsorted,
and feeds a ``DataLoader`` whose worker processes re-do that math per item.
A TPU input pipeline wants whole static-shape *rounds*, so the base class
here is an array store:

- training data lives as flat numpy arrays sorted by client, described by
  ``images_per_client`` (the natural partition);
- ``data_per_client`` re-partitions for iid mode (global permutation split
  evenly — reference fed_dataset.py:30-39) or for splitting each natural
  client/class across ``num_clients // num_natural`` synthetic clients
  (reference fed_dataset.py:41-48);
- ``gather(flat_idx)`` materializes any index array into batch arrays in one
  vectorized fancy-index, so a whole round is built host-side in one call.

Subclasses provide ``prepare_datasets`` (one-time on-disk conversion, same
protocol as the reference: per-client files + ``stats.json``) and the raw
array loading.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from commefficient_tpu.telemetry import tracing


class FedDataset:
    # number of natural clients this dataset always produces, or None when
    # data-dependent; used to validate legacy-layout adoption
    expected_natural_clients: Optional[int] = None

    def __init__(self, dataset_dir: str, train: bool = True,
                 do_iid: bool = False, num_clients: Optional[int] = None,
                 transform=None, download: bool = False, seed: int = 0):
        if not do_iid and num_clients == 1:
            raise ValueError("can't have 1 client when non-iid")
        self.dataset_dir = dataset_dir
        self.train = train
        self.do_iid = do_iid
        self._num_clients = num_clients
        self.transform = transform

        # Legacy-layout detection, decided ONCE: a directory prepared by the
        # reference (or pre-rename versions of this package) holds a plain
        # stats.json + unprefixed data files and is read as-is. Anything this
        # package prepares is written under class-prefixed names, so legacy
        # files are never overwritten and classes sharing a dataset_dir stay
        # isolated.
        self._legacy_layout = (
            not os.path.exists(self._prefixed_stats_fn())
            and os.path.exists(os.path.join(dataset_dir, "stats.json")))
        if self._legacy_layout and self.expected_natural_clients is not None:
            # a legacy stats.json carries no class identity; only adopt it
            # when its client count matches this dataset's natural partition
            # (10 for CIFAR10, 100 for CIFAR100, ...) — otherwise it belongs
            # to some other dataset and this class prepares its own shards.
            # Malformed/foreign stats never block construction.
            try:
                with open(os.path.join(dataset_dir, "stats.json")) as f:
                    n_legacy = len(json.load(f)["images_per_client"])
            except (json.JSONDecodeError, KeyError, TypeError, OSError):
                n_legacy = -1
            if n_legacy != self.expected_natural_clients:
                self._legacy_layout = False

        if not os.path.exists(self.stats_fn()):
            self.prepare_datasets(download=download)
        try:
            self._load_meta()
            self._load_arrays()
        except FileNotFoundError as e:
            # stats exist but array files are missing (partially-deleted
            # directory): re-prepare once — under prefixed names — and
            # reload. Loud on purpose: if the raw source is also gone, the
            # subclass's synthetic fallback will print its own warning and
            # the user must not mistake the result for their original data.
            print(f"WARNING: prepared arrays missing ({e}); re-preparing "
                  f"{type(self).__name__} under {self.dataset_dir}")
            self._legacy_layout = False
            self.prepare_datasets(download=download)
            self._load_meta()
            self._load_arrays()

        if do_iid:
            # iid = a fixed global permutation re-dealt evenly to clients
            # (reference fed_dataset.py:27-28, 64-68)
            self.iid_shuffle = np.random.RandomState(seed).permutation(
                len(self))

    def _invalidate_stale_synth_prep(self, dataset_dir: str,
                                     synthetic) -> None:
        """Synthetic-prep invalidation, shared by every dataset with a
        synthetic fallback (was duplicated near-verbatim in FedCIFAR and
        FedEMNIST — ADVICE r4). Call BEFORE super().__init__ from a
        subclass that defines ``_has_real_source`` and ``_synth_marker``.

        A prepared cache under OUR prefixed stats records the generator
        marker it was built with; a mismatch (knob change, generator fix)
        unlinks the stats so __init__ re-prepares. Marker-less stats:

        - with a real raw source present they may be real-data preps whose
          provenance we cannot verify — preserved with a warning;
        - with NO real source and a synthetic prep requested, they are
          almost certainly a stale pre-marker synthetic cache, and
          silently reusing one reproduces the exact failure the markers
          exist to prevent (e.g. val accuracy pinned at chance on pre-fix
          EMNIST prototypes) — re-prepared (ADVICE r4). Re-preparation is
          NON-DESTRUCTIVE: the old prefixed stats + data files are
          renamed to ``*.pre-marker.bak`` first, because this case can
          also be a real-data prep whose raw source was deleted to save
          space — irreplaceable, and a user who hits that can rename the
          .bak files back.
        """
        pref = os.path.join(dataset_dir,
                            f"stats_{type(self).__name__}.json")
        if not os.path.exists(pref):
            return
        try:
            with open(pref) as f:
                marker = json.load(f).get("synthetic")
        except Exception:
            marker = None
        has_real = self._has_real_source(dataset_dir)
        want_syn = (synthetic is True
                    or (synthetic is None and not has_real))
        expected = self._synth_marker() if want_syn else None
        if marker is not None and marker != expected:
            os.unlink(pref)       # ours and stale: re-prepare
        elif marker is None and want_syn:
            # rename-aside only when no reference-style legacy stats.json
            # could take over: removing the prefixed stats would otherwise
            # flip __init__ into legacy-layout ADOPTION (loading the
            # legacy arrays instead of re-preparing), contradicting the
            # warning below
            legacy_present = os.path.exists(
                os.path.join(dataset_dir, "stats.json"))
            if not has_real and not legacy_present:
                print(f"WARNING: prepared data under {dataset_dir} "
                      "predates synthetic-prep markers and no real raw "
                      "source is present: treating it as a stale "
                      "synthetic cache and re-preparing (the old files "
                      "are kept as *.pre-marker.bak in case this was a "
                      "real-data prep whose raw source was removed)")
                import glob as _glob
                prefix = type(self).__name__
                for fn in _glob.glob(
                        os.path.join(dataset_dir, f"{prefix}_*")) + [pref]:
                    if ".pre-marker.bak" in fn:
                        continue
                    # never clobber an earlier run's preserved backup
                    # (os.replace silently overwrites): suffix with a
                    # counter so the FIRST backup — the one that may hold
                    # a real-data prep — survives every re-preparation
                    dst = fn + ".pre-marker.bak"
                    n = 1
                    while os.path.exists(dst):
                        dst = fn + f".pre-marker.bak.{n}"
                        n += 1
                    if n > 1:
                        print(f"WARNING: {fn + '.pre-marker.bak'} already "
                              f"exists; keeping new backup as {dst}")
                    os.replace(fn, dst)
            else:
                print(f"WARNING: reusing prepared data under {dataset_dir} "
                      "that predates synthetic-prep markers; delete "
                      f"{pref} to regenerate with the current synthetic "
                      "settings")

    # ---------------------------------------------------------------- meta

    def _prefixed_stats_fn(self) -> str:
        # namespaced per dataset class: several datasets may share one
        # dataset_dir (the drivers' default is ./dataset for all), and one
        # dataset's stats must not make another skip its preparation
        return os.path.join(self.dataset_dir,
                            f"stats_{type(self).__name__}.json")

    def stats_fn(self) -> str:
        if getattr(self, "_legacy_layout", False):
            return os.path.join(self.dataset_dir, "stats.json")
        return self._prefixed_stats_fn()

    def data_fn(self, name: str) -> str:
        """Resolve a prepared-data filename: the class-prefixed name, or the
        reference's unprefixed name when this directory was detected as a
        coherent legacy layout at init (read path only — writes always go
        through the prefixed name because prepare_datasets clears the
        flag before dispatching to the subclass)."""
        if getattr(self, "_legacy_layout", False):
            return os.path.join(self.dataset_dir, name)
        return os.path.join(self.dataset_dir,
                            f"{type(self).__name__}_{name}")

    def _load_meta(self) -> None:
        with open(self.stats_fn()) as f:
            stats = json.load(f)
        self.images_per_client = np.array(stats["images_per_client"],
                                          dtype=np.int64)
        self.num_val_images = int(stats["num_val_images"])

    @property
    def num_clients(self) -> int:
        return (self._num_clients if self._num_clients is not None
                else len(self.images_per_client))

    @property
    def data_per_client(self) -> np.ndarray:
        """Per-synthetic-client datum counts (reference fed_dataset.py:29-48)."""
        if self.do_iid:
            n = len(self)
            per = np.full(self.num_clients, n // self.num_clients,
                          dtype=np.int64)
            per[self.num_clients - n % self.num_clients:] += 1
            return per
        if self._num_clients is None:
            return self.images_per_client
        natural = len(self.images_per_client)
        if self.num_clients % natural != 0:
            # the resharding scheme splits every natural client (class)
            # across num_clients / natural synthetic clients; anything else
            # would silently produce a different client count than
            # requested (latent in reference fed_dataset.py:41-48)
            raise ValueError(
                f"non-iid num_clients ({self.num_clients}) must be a "
                f"multiple of the natural client count ({natural}); "
                "use --iid for arbitrary client counts")
        out = []
        shards = self.num_clients // natural
        for num_images in self.images_per_client:
            counts = [num_images // shards] * shards
            counts[-1] += num_images % shards
            out.extend(counts)
        return np.array(out, dtype=np.int64)

    def __len__(self) -> int:
        if self.train:
            return int(self.images_per_client.sum())
        return self.num_val_images

    # -------------------------------------------------------------- arrays

    def _load_arrays(self) -> None:
        """Populate ``self.arrays``: dict of numpy arrays with a common
        leading flat-index axis (train: sorted by natural client)."""
        raise NotImplementedError

    def prepare_datasets(self, download: bool = False) -> None:
        # preparation ALWAYS writes the class-prefixed layout — clear the
        # legacy flag up front so data_fn never resolves a write to a
        # legacy (reference-owned) filename
        self._legacy_layout = False
        self._prepare(download=download)

    def _prepare(self, download: bool = False) -> None:
        raise NotImplementedError

    def gather(self, flat_idx: np.ndarray) -> Dict[str, np.ndarray]:
        """Fancy-index every array; under iid the flat index is routed
        through the global permutation first (reference fed_dataset.py:64-68).
        Accepts any index shape; output leaves have that leading shape.
        The host_gather span is the host data pipeline's wall time — on
        runs without a DeviceStore this is the input cost the round
        pipeline (core/pipeline.py) moves OFF the critical path.

        Prefetch contract: the round pipeline calls this from its single
        worker thread, one call per round in round order — exactly the
        inline call sequence — so stateful host-transform RNGs (e.g.
        CifarTrain's per-call draws) advance identically pipelined or
        not. Never share one FedDataset between two concurrent
        consumers; per-call determinism is sequential, not locked."""
        with tracing.span("host_gather"):
            return self._gather(flat_idx)

    def _gather(self, flat_idx: np.ndarray) -> Dict[str, np.ndarray]:
        idx = np.asarray(flat_idx)
        if self.train and self.do_iid:
            idx = self.iid_shuffle[idx]
        # fused native gather+augment for the image leaf when the C++
        # data-plane is available (data/native.py)
        fused_image = None
        if (self.transform is not None
                and hasattr(self.transform, "gather_fused")
                and "image" in self.arrays):
            fused_image = self.transform.gather_fused(
                self.arrays["image"], idx)
        if fused_image is not None:
            out = {k: v[idx] for k, v in self.arrays.items()
                   if k != "image"}
            out["image"] = fused_image
            return out
        out = {k: v[idx] for k, v in self.arrays.items()}
        if self.transform is not None:
            out = self.transform(out)
        return out

    # ------------------------------------------------------------- helpers

    def write_stats(self, images_per_client, num_val_images: int,
                    **extra) -> None:
        os.makedirs(self.dataset_dir, exist_ok=True)
        stats = {"images_per_client": [int(x) for x in images_per_client],
                 "num_val_images": int(num_val_images), **extra}
        with open(self._prefixed_stats_fn(), "w") as f:
            json.dump(stats, f)
