"""Federated data layer: client-partitioned datasets + round samplers.

Registry mirrors the reference's ``globals()["Fed" + name]`` lookup
(cv_train.py:262, gpt2_train.py:316).
"""

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.data.fed_sampler import FedSampler, ValSampler, Round
from commefficient_tpu.data.fed_cifar import FedCIFAR10, FedCIFAR100
from commefficient_tpu.data.fed_emnist import FedEMNIST
from commefficient_tpu.data.fed_imagenet import FedImageNet
from commefficient_tpu.data.fed_persona import FedPERSONA, persona_collate
from commefficient_tpu.data.scenarios import (CohortFate, StragglerScenario,
                                              make_scenario)
from commefficient_tpu.data.transforms import transforms_for

_REGISTRY = {
    "CIFAR10": FedCIFAR10,
    "CIFAR100": FedCIFAR100,
    "EMNIST": FedEMNIST,
    "ImageNet": FedImageNet,
    "PERSONA": FedPERSONA,
}


def get_dataset(name: str):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown dataset {name!r}; "
                         f"choices: {sorted(_REGISTRY)}") from None


__all__ = [
    "FedDataset",
    "FedSampler",
    "ValSampler",
    "Round",
    "FedCIFAR10",
    "FedCIFAR100",
    "FedEMNIST",
    "FedImageNet",
    "FedPERSONA",
    "persona_collate",
    "CohortFate",
    "StragglerScenario",
    "make_scenario",
    "transforms_for",
    "get_dataset",
]
