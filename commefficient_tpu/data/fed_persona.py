"""Federated PersonaChat: clients are distinct personalities.

Parity target: reference ``FedPERSONA`` (CommEfficient/data_utils/
fed_persona.py:31-392): 17,568 natural clients (one per personality), items
are next-utterance-classification instances — ``num_candidates`` candidate
replies (gold last), each encoded as persona ⊕ dialogue history ⊕ reply with
``<speaker1>/<speaker2>`` segment tokens; model inputs are the 5-tuple
``input_ids, mc_token_ids, lm_labels, mc_labels, token_type_ids``
(fed_persona.py:27-28) padded per batch (``personachat_collate_fn``,
360-392).

TPU-native re-design: tokenization happens ONCE in ``prepare_datasets``
(the reference re-reads and re-tokenizes per-client json on every
``__getitem__``, fed_persona.py:218-221 — a noted bottleneck); items are
packed into flat int32 arrays padded to a *static* ``max_seq_len``, so each
round is one fancy-index gather.

Offline tokenizer: a real GPT-2 BPE is used when its vocab files are on
disk; otherwise ``HashTokenizer`` (stable word-hash buckets) keeps the whole
pipeline runnable in zero-egress environments. Synthetic dialogue generation
stands in for the S3 download (fed_persona.py:23) the environment forbids.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

SPECIAL_TOKENS = ["<bos>", "<eos>", "<speaker1>", "<speaker2>", "<pad>"]
LM_IGNORE = -100


class HashTokenizer:
    """Deterministic word-level hash tokenizer (offline fallback).

    Stable across processes (crc32, not python ``hash``); special tokens
    occupy the top ids like the reference's resized GPT-2 table."""

    def __init__(self, base_vocab: int = 8192):
        self.base_vocab = base_vocab
        self.special = {t: base_vocab + i for i, t in
                        enumerate(SPECIAL_TOKENS)}

    def __len__(self):
        return self.base_vocab + len(SPECIAL_TOKENS)

    def encode(self, text: str) -> List[int]:
        return [zlib.crc32(w.lower().encode()) % self.base_vocab
                for w in text.split()]

    def convert_tokens_to_ids(self, tokens):
        if isinstance(tokens, str):
            return self.special[tokens]
        return [self.special[t] for t in tokens]


def get_tokenizer(model_checkpoint: str = "gpt2"):
    """GPT-2 BPE when available locally, HashTokenizer otherwise."""
    try:
        from transformers import GPT2Tokenizer
        tok = GPT2Tokenizer.from_pretrained(model_checkpoint,
                                            local_files_only=True)
        tok.add_special_tokens({
            "bos_token": "<bos>", "eos_token": "<eos>",
            "pad_token": "<pad>",
            "additional_special_tokens": ["<speaker1>", "<speaker2>"]})
        return tok
    except Exception:
        return HashTokenizer()


def build_input_from_segments(persona: Sequence[List[int]],
                              history: Sequence[List[int]],
                              reply: List[int], tokenizer,
                              lm_labels: bool = False) -> Dict:
    """Assemble one candidate sequence (reference fed_persona.py:330-358):
    ``<bos> persona <speaker2/1 alternating> history ... <speaker2> reply
    <eos>``; token types mark each segment with its speaker token; LM labels
    cover only the gold reply (+ <eos>)."""
    bos, eos, spk1, spk2 = [
        tokenizer.convert_tokens_to_ids(t) for t in SPECIAL_TOKENS[:4]]
    seqs = [[bos] + [t for s in persona for t in s]]
    for i, h in enumerate(history):
        spk = spk2 if (len(history) - i) % 2 == 1 else spk1
        seqs.append([spk] + h)
    seqs.append([spk2] + reply + [eos])

    words, types = [], []
    for seq in seqs:
        spk = spk2 if seq and seq[0] == spk2 else spk1
        words.extend(seq)
        types.extend([spk] * len(seq))
    labels = [LM_IGNORE] * (len(words) - len(seqs[-1]) + 1) + seqs[-1][1:]
    return {"input_ids": words, "token_type_ids": types,
            "lm_labels": labels if lm_labels else [LM_IGNORE] * len(words)}


def _synthetic_personachat(num_personalities: int = 12,
                           dialogs_per: int = 3, seed: int = 5):
    rng = np.random.RandomState(seed)
    words = ["i", "like", "cats", "dogs", "music", "pizza", "running",
             "books", "you", "do", "what", "love", "my", "hobby", "is"]

    def sent():
        return " ".join(rng.choice(words, size=rng.randint(3, 7)))

    data = []
    for p in range(num_personalities):
        personality = [sent() for _ in range(4)]
        utterances = []
        history = []
        for _ in range(dialogs_per):
            history = history + [sent()]
            utterances.append({
                "history": list(history),
                "candidates": [sent(), sent()],  # gold last
            })
        data.append({"personality": personality, "utterances": utterances})
    return data


class FedPERSONA(FedDataset):
    """dataset_dir layout: ``personachat_self_original.json`` (the standard
    release: {"train": [...], "valid": [...]}) or synthetic fallback."""

    def __init__(self, *args, tokenizer=None, num_candidates: int = 2,
                 max_seq_len: int = 128, max_history: int = 2,
                 personality_permutations: int = 1,
                 synthetic: Optional[bool] = None, **kw):
        self.tokenizer = tokenizer or HashTokenizer()
        self.num_candidates = num_candidates
        self.max_seq_len = max_seq_len
        # history truncation to the last 2*max_history+1 exchanges
        # (reference fed_persona.py:255) and persona-rotation augmentation
        # (--personality_permutations, reference utils.py:204-207)
        self.max_history = max_history
        self.personality_permutations = personality_permutations
        self._synthetic = synthetic
        # the packed npz bakes these knobs — and the tokenizer vocabulary
        # and corpus source — in at prepare time; changing any of them must
        # invalidate the cache, not be silently ignored
        self.dataset_dir = args[0] if args else kw.get("dataset_dir")
        corpus_json = os.path.join(self.dataset_dir,
                                   "personachat_self_original.json")
        self._prep_config = {
            "num_candidates": num_candidates,
            "max_seq_len": max_seq_len,
            "max_history": max_history,
            "personality_permutations": personality_permutations,
            "tokenizer": [type(self.tokenizer).__name__,
                          len(self.tokenizer)],
            "corpus": ("real" if (os.path.exists(corpus_json)
                                  and not synthetic) else "synthetic"),
        }
        # prep-config staleness check. The cfg sidecar lives under the
        # class-prefixed name (write policy of fed_dataset.data_fn); a plain
        # persona_prep.json is read as a legacy layout's sidecar. A cache
        # with NO sidecar but an existing packed npz was written by a
        # pre-sidecar version whose packing semantics differ (no history
        # truncation, no permutations) — it can never match the current
        # config, so it is stale by definition and must re-prepare rather
        # than be silently adopted.
        # data_fn resolves to the prefixed name here (_legacy_layout is not
        # set yet), which is exactly the write-policy name _prepare will use
        cfg_pref = self.data_fn("persona_prep.json")
        cfg_legacy = os.path.join(self.dataset_dir, "persona_prep.json")
        npz_pref = self.data_fn("persona_train.npz")
        npz_legacy = os.path.join(self.dataset_dir, "persona_train.npz")
        val_legacy = os.path.join(self.dataset_dir, "persona_val.npz")
        saved_cfg = cfg_src = None
        for fn in (cfg_pref, cfg_legacy):
            if os.path.exists(fn):
                with open(fn) as f:
                    saved_cfg = json.load(f)
                cfg_src = fn
                break
        have_pack = os.path.exists(npz_pref) or os.path.exists(npz_legacy)
        stale = (saved_cfg != self._prep_config if saved_cfg is not None
                 else have_pack)
        if (not stale and cfg_src == cfg_legacy
                and os.path.exists(npz_legacy)
                and not os.path.exists(npz_pref)
                and os.path.exists(self._prefixed_stats_fn())):
            # mixed layout from the immediately previous version (prefixed
            # stats via write_stats, but unprefixed pack + sidecar): the
            # pack matches this config, so adopt it by renaming into the
            # prefixed scheme instead of re-tokenizing the whole corpus
            os.rename(npz_legacy, npz_pref)
            if os.path.exists(val_legacy):
                os.rename(val_legacy, self.data_fn("persona_val.npz"))
            os.rename(cfg_legacy, cfg_pref)
        if stale:
            # force re-preparation: remove whichever stats file would
            # satisfy the prepared-check. The prefixed one is unambiguously
            # ours; a pre-rename plain stats.json is removed only when it
            # demonstrably describes the persona npz (total item count
            # matches) — in a shared dir it may belong to another dataset's
            # legacy layout.
            pref = self._prefixed_stats_fn()
            if os.path.exists(pref):
                os.unlink(pref)
            plain = os.path.join(self.dataset_dir, "stats.json")
            if os.path.exists(plain) and os.path.exists(npz_legacy):
                try:
                    with open(plain) as pf:
                        n_stats = sum(json.load(pf)["images_per_client"])
                    with np.load(npz_legacy) as z:
                        n_items = len(z["mc_label"])
                except Exception:
                    n_stats, n_items = -1, -2
                if n_stats == n_items:
                    os.unlink(plain)
            # a stale pack must never be adoptable (silent adoption is the
            # bug this block closes): persona_*.npz / persona_prep.json are
            # only ever written by this package, so renaming them out of
            # the adoption path is safe even when the plain stats.json
            # (possibly another dataset's) has to stay. Rename, don't
            # delete: if re-preparation falls back to synthetic data (the
            # real corpus json may be gone), the original pack is still
            # recoverable from the .stale files.
            for fn in (npz_legacy, val_legacy, cfg_legacy):
                if os.path.exists(fn):
                    os.replace(fn, fn + ".stale")
        super().__init__(*args, **kw)

    # --------------------------------------------------------- preparation

    def _raw_corpus(self):
        fn = os.path.join(self.dataset_dir, "personachat_self_original.json")
        if os.path.exists(fn) and not self._synthetic:
            with open(fn) as f:
                blob = json.load(f)
            return blob["train"], blob["valid"]
        if self._synthetic is False:
            raise FileNotFoundError(f"no personachat json under "
                                    f"{self.dataset_dir}")
        if self._synthetic is None:
            print(f"WARNING: no personachat json under {self.dataset_dir}; "
                  "generating synthetic dialogues")
        return (_synthetic_personachat(12, 3, seed=5),
                _synthetic_personachat(4, 2, seed=6))

    def _pack_split(self, dialogs, by_personality: bool,
                    permutations: int = 1):
        tok = self.tokenizer
        C, S = self.num_candidates, self.max_seq_len
        enc = lambda s: tok.encode(s)

        # group dialogs by personality => natural clients
        # (reference fed_persona.py: clients are distinct personalities)
        groups: Dict[str, list] = {}
        for d in dialogs:
            key = "\n".join(d["personality"]) if by_personality else "all"
            groups.setdefault(key, []).append(d)

        rows = {"input_ids": [], "token_type_ids": [], "lm_labels": [],
                "mc_token_ids": [], "mc_label": []}
        per_client = []
        pad_id = tok.convert_tokens_to_ids("<pad>")
        for key in sorted(groups):
            n_items = 0
            for d in groups[key]:
                persona_base = [enc(s) for s in d["personality"]]
                # tokenize history/candidates ONCE; only the persona order
                # differs between permutations
                utts = [
                    ([enc(h) for h in
                      utt["history"][-(2 * self.max_history + 1):]],
                     [enc(c) for c in utt["candidates"][-C:]])
                    for utt in d["utterances"]]
                # persona rotation: permutation p sees the sentences rotated
                # by p (TransferTransfo augmentation the reference exposes
                # as --personality_permutations; train split only)
                for perm in range(permutations):
                    persona = persona_base[perm:] + persona_base[:perm]
                    for history, cands in utts:
                        self._append_item(rows, persona, history, cands,
                                          pad_id, C, S)
                        n_items += 1
            per_client.append(n_items)
        packed = {k: np.stack(v).astype(np.int32)
                  for k, v in rows.items()}
        return packed, per_client

    def _append_item(self, rows, persona, history, cands, pad_id, C, S):
        tok = self.tokenizer
        ii = np.full((C, S), pad_id, np.int32)
        tt = np.full((C, S), pad_id, np.int32)
        ll = np.full((C, S), LM_IGNORE, np.int32)
        mc = np.zeros((C,), np.int32)
        for j, cand_ids in enumerate(cands):
            gold = j == len(cands) - 1
            inst = build_input_from_segments(
                persona, history, cand_ids, tok, lm_labels=gold)
            ids = inst["input_ids"][:S]
            ii[j, :len(ids)] = ids
            tt[j, :len(ids)] = inst["token_type_ids"][:S]
            ll[j, :len(ids)] = inst["lm_labels"][:S]
            mc[j] = len(ids) - 1
        rows["input_ids"].append(ii)
        rows["token_type_ids"].append(tt)
        rows["lm_labels"].append(ll)
        rows["mc_token_ids"].append(mc)
        rows["mc_label"].append(len(cands) - 1)

    def _prepare(self, download: bool = False) -> None:
        train_raw, val_raw = self._raw_corpus()
        train, per_client = self._pack_split(
            train_raw, by_personality=True,
            permutations=self.personality_permutations)
        # validation is never augmented (the reference permutes training
        # personalities only)
        val, _ = self._pack_split(val_raw, by_personality=True)
        os.makedirs(self.dataset_dir, exist_ok=True)
        # class-prefixed writes via data_fn (prepare_datasets cleared the
        # legacy flag, so these resolve to FedPERSONA_-prefixed names — the
        # write policy fed_dataset.py:110-119 establishes for every dataset)
        np.savez(self.data_fn("persona_train.npz"), **train)
        np.savez(self.data_fn("persona_val.npz"), **val)
        with open(self.data_fn("persona_prep.json"), "w") as f:
            json.dump(self._prep_config, f)
        self.write_stats(per_client, len(val["mc_label"]))

    def _load_arrays(self) -> None:
        fn = "persona_train.npz" if self.train else "persona_val.npz"
        with np.load(self.data_fn(fn)) as d:
            self.arrays = {k: d[k] for k in d.files}


def persona_collate(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """The arrays are already padded/stacked statically; collate is the
    identity (kept for API parity with ``personachat_collate_fn``,
    reference fed_persona.py:360-392)."""
    return batch
