"""Device-resident dataset store: upload once, index + augment on device.

Why this exists
---------------
The reference streams every round's batch host->GPU and reads metrics back
per round (fed_worker.py:41, cv_train.py:193-229) — cheap over PCIe. On this
TPU runtime a single host<->device transfer costs ~170 ms of LATENCY
regardless of size, so a per-round upload+fetch pair dominates the 50 ms
federated round ~10x. The TPU-native discipline (SURVEY.md §7 "hard parts":
keep state resident, fetch only metrics) extends to the DATA: raw uint8
arrays are uploaded once (CIFAR-10 train is 150 MB), each round's batch is
gathered and augmented ON DEVICE from tiny resident index arrays, and the
driver fetches nothing until the epoch ends.

On-device augmentation mirrors data/transforms.py in kind (reflect-pad-4 +
random crop + horizontal flip + per-channel normalize, the cifar10_fast
recipe) but draws its randomness from a jax PRNG key, so augmentation draws differ from the host pipeline — irrelevant for
training quality, and the eval path (normalize only) is exactly equal.

Scope: image-classification stores (CIFAR/EMNIST/ImageNet-style uint8 or
float images + int targets) and identity stores (already-tokenized
persona int arrays). Anything else falls back to the host pipeline.
ImageNet 224^2 rides the same machinery with a flip+normalize train
augment ("imagenet_train"): the uint8 store plus the fused on-device
normalize removes the per-round host input copy whose lane-padded
(C=3 -> 128) layout the round trace attributed 4.8-9.6 ms/round to
(runs/BREAKDOWN_imagenet.md).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.telemetry import tracing


def _arrays_nbytes(arrays) -> int:
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in arrays.values())


class DeviceStore:
    """Uploads a dataset's arrays once; serves jitted round batches.

    Parameters
    ----------
    arrays : dict of numpy arrays with a common leading flat-index axis
        (a ``FedDataset.arrays``); uploaded verbatim (uint8 stays uint8).
    iid_shuffle : optional global permutation (``FedDataset.iid_shuffle``) —
        applied on device so host round indices stay the sampler's.
    augment : "cifar_train" (reflect-pad-4 crop + flip + normalize),
        "emnist_train" (edge-pad-2 crop + normalize), "normalize", or
        None. Crop parameters are fixed per kind (``_SHIFT_CROP``),
        mirroring the host stacks in data/transforms.py.
    mean, std : per-channel normalization constants (for the image leaf).
    """

    def __init__(self, arrays: Dict[str, np.ndarray],
                 iid_shuffle: Optional[np.ndarray] = None,
                 augment: Optional[str] = None,
                 mean=None, std=None,
                 mesh=None, shard_axis: Optional[str] = None,
                 out_shardings=None):
        if mesh is not None:
            # mesh mode: the resident arrays REPLICATE across the mesh (a
            # CIFAR train set is ~150 MB — cheap next to model state) and
            # the batch jit emits its output already sharded over the
            # round's client axis: each device gathers + augments only its
            # own W/n clients' rows, so the multi-chip round keeps the
            # upload-once / no-host-streaming discipline (VERDICT r1 weak
            # #3 — the mesh branch used to fall back to per-round host
            # streaming).
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(mesh, P())
            # train stores shard the emitted batch over the round's client
            # axis (pass shard_axis); val stores emit replicated (the val
            # step is an unsharded jit and valid_batch_size need not divide
            # the mesh)
            self._out_sharding = (NamedSharding(mesh, P(shard_axis))
                                  if shard_axis else rep)
            put = lambda a: jax.device_put(jnp.asarray(a), rep)
        else:
            self._out_sharding = None
            put = jnp.asarray
        self.arrays = {k: put(v) for k, v in arrays.items()}
        self.iid_shuffle = (put(np.asarray(iid_shuffle, np.int32))
                            if iid_shuffle is not None else None)
        self.augment = augment
        self.mean = (jnp.asarray(mean, jnp.float32)
                     if mean is not None else None)
        self.std = jnp.asarray(std, jnp.float32) if std is not None else None
        if out_shardings is not None:
            # explicit per-leaf layout (e.g. the runtime's seq-sharded
            # batch shardings) — must match what the round jit expects
            self._batch = jax.jit(self._batch_impl,
                                  out_shardings=out_shardings)
        elif self._out_sharding is not None:
            out_sh = jax.tree.map(lambda _: self._out_sharding, arrays)
            self._batch = jax.jit(self._batch_impl, out_shardings=out_sh)
        else:
            self._batch = jax.jit(self._batch_impl)

    @property
    def nbytes(self) -> int:
        return _arrays_nbytes(self.arrays)

    # ------------------------------------------------------------- internals

    # augment kind -> (crop pad, jnp.pad mode, horizontal flip); mirrors
    # the host stacks in data/transforms.py (CifarTrain / FemnistTrain)
    _SHIFT_CROP = {"cifar_train": (4, "reflect", True),
                   "emnist_train": (2, "edge", False)}
    # flip-only kinds (no shift crop); mirrors ImagenetTrain — the store
    # is pre-sized at prepare time, so train augmentation is a horizontal
    # flip + normalize, all fused into the gather jit. The resident array
    # stays uint8 (4x smaller than float32 at 224^2, and the round's
    # input arrives as a device-produced value instead of a host copy —
    # the lane-padded C=3->128 input transfer the ImageNet trace blamed,
    # runs/BREAKDOWN_imagenet.md)
    _FLIP_ONLY = ("imagenet_train",)

    def _transform_images(self, img: jax.Array, rng) -> jax.Array:
        x = img.astype(jnp.float32)
        if img.dtype == jnp.uint8:   # raw 0..255 bytes
            x = x / 255.0
        if self.augment in self._FLIP_ONLY:
            H, W, C = x.shape[-3:]
            flat = x.reshape((-1, H, W, C))
            do_flip = jax.random.bernoulli(rng, 0.5, (flat.shape[0],))
            flat = jnp.where(do_flip[:, None, None, None],
                             flat[:, :, ::-1, :], flat)
            x = flat.reshape(x.shape)
        if self.augment in self._SHIFT_CROP:
            p, pad_mode, flip = self._SHIFT_CROP[self.augment]
            H, W, C = x.shape[-3:]
            flat = x.reshape((-1, H, W, C))
            n = flat.shape[0]
            k1, k2 = jax.random.split(rng)
            padded = jnp.pad(flat, ((0, 0), (p, p), (p, p), (0, 0)),
                             mode=pad_mode)
            offs = jax.random.randint(k1, (n, 2), 0, 2 * p + 1)

            def crop_one(im, off):
                return jax.lax.dynamic_slice(
                    im, (off[0], off[1], 0), (H, W, C))

            flat = jax.vmap(crop_one)(padded, offs)
            if flip:
                do_flip = jax.random.bernoulli(k2, 0.5, (n,))
                flat = jnp.where(do_flip[:, None, None, None],
                                 flat[:, :, ::-1, :], flat)
            x = flat.reshape(x.shape)
        if self.mean is not None:
            x = (x - self.mean) / self.std
        return x

    def _batch_impl(self, flat_idx: jax.Array, rng) -> Dict[str, jax.Array]:
        idx = flat_idx
        if self.iid_shuffle is not None:
            idx = self.iid_shuffle[idx]
        out = {}
        for k, a in self.arrays.items():
            leaf = a[idx]
            if k == "image" and self.augment is not None:
                leaf = self._transform_images(leaf, rng)
            out[k] = leaf
        return out

    # -------------------------------------------------------------- user API

    def round_batch(self, flat_idx, rng) -> Dict[str, jax.Array]:
        """Device batch for the given (host or device) index array; all
        compute and memory traffic stays on device. The span covers the
        index upload + the async gather/augment dispatch — a long
        data_gather span against a short round means the batch jit (not
        the round) owns the input-wait fraction."""
        with tracing.span("data_gather"):
            return self._batch(jnp.asarray(flat_idx, jnp.int32), rng)


_AUGMENT_FOR = {
    # dataset_name -> (train_augment, normalize-constant prefix).
    # ImageNet's host transform (ImagenetTrain) is flip + normalize on
    # pre-sized crops — its device equivalent is "imagenet_train", so
    # 224^2 train batches are gathered, flipped and normalized ON DEVICE
    # from the uint8-resident store instead of streaming a float32 (and
    # lane-padded, C=3->128) host copy every round. A real-size ImageNet
    # (190 GB uint8) still exceeds max_bytes and falls back to the host
    # pipeline, where the round pipeline (core/pipeline.py) hides the
    # gather instead.
    "CIFAR10": ("cifar_train", "CIFAR10"),
    "CIFAR100": ("cifar_train", "CIFAR100"),
    "EMNIST": ("emnist_train", "FEMNIST"),
    "ImageNet": ("imagenet_train", "IMAGENET"),
    "PERSONA": (None, None),
}


def make_device_store(dataset, dataset_name: str, train: bool,
                      max_bytes: int = 2 << 30,
                      mesh=None, out_shardings=None,
                      no_augment: bool = False) -> Optional[DeviceStore]:
    """Build a DeviceStore for a FedDataset when its arrays fit on device
    and the dataset's transform has a device equivalent; None => use the
    host pipeline. With a ``mesh``, arrays replicate across it and train
    batches come out sharded over the round's client axis.
    ``no_augment``: train batches get normalize-only (the hard synthetic
    regime's per-pixel class evidence does not survive crop/flip —
    cv_train.build_datasets)."""
    from commefficient_tpu.data import transforms as T

    if dataset_name not in _AUGMENT_FOR:
        return None
    aug, const = _AUGMENT_FOR[dataset_name]
    if no_augment and aug not in (None, "host"):
        aug = "normalize"
    if train and aug == "host":
        return None
    mean = getattr(T, f"{const}_MEAN", None) if const else None
    std = getattr(T, f"{const}_STD", None) if const else None
    if _arrays_nbytes(dataset.arrays) > max_bytes:
        return None
    return DeviceStore(
        dataset.arrays,
        iid_shuffle=(dataset.iid_shuffle
                     if getattr(dataset, "do_iid", False) and train
                     else None),
        augment=(aug if train else ("normalize" if aug else None)),
        mean=mean, std=std, mesh=mesh,
        shard_axis=(mesh.axis_names[0] if mesh is not None and train
                    else None),
        out_shardings=(out_shardings if train else None))
