"""Federated ImageNet: each wnid class is one natural client.

Parity target: reference ``FedImageNet`` (CommEfficient/data_utils/
fed_imagenet.py:12-76), which wraps torchvision's ``ImageNet`` folder layout
and only generates ``stats.json`` (no download, fed_imagenet.py:16, 22-23).

TPU-native design: full-resolution JPEG decode belongs in a one-time prepare
pass, not the per-round hot path. ``prepare_datasets`` walks a
``train/<wnid>/*`` image tree (decoding via PIL when available), center-crops
to ``image_size`` and packs per-client uint8 npy shards in the same
client-file layout as FedCIFAR; ``synthetic=True`` generates a small stand-in
tree. The per-round path is then identical to CIFAR: one vectorized gather.

The prepared arrays stay **uint8 end to end**: when the set fits the
device-store budget, the round batch is gathered, flipped and normalized
ON DEVICE ("imagenet_train" augment, data/device_store.py) — no per-round
float32 host input copy, which at 224^2 transferred with the C=3 channel
lane-padded to 128 (~42x inflation, 4.8-9.6 ms/round in the committed
trace, runs/BREAKDOWN_imagenet.md). Oversized sets fall back to the host
gather, which the round pipeline (core/pipeline.py) overlaps with device
execution instead.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from commefficient_tpu.data.fed_cifar import FedCIFAR10, _synthetic_cifar


class FedImageNet(FedCIFAR10):
    # a legacy dir is adopted only at the standard ImageNet class count —
    # without this override the inherited value (10) would adopt CIFAR dirs
    expected_natural_clients = 1000
    num_classes = 1000

    def __init__(self, *args, image_size: int = 224,
                 synthetic_num_classes: int = 8, **kw):
        self.image_size = image_size
        self._synthetic_num_classes = synthetic_num_classes
        super().__init__(*args, **kw)

    @classmethod
    def _has_real_source(cls, dataset_dir: str) -> bool:
        return os.path.isdir(os.path.join(dataset_dir, "train"))

    def _synth_marker(self) -> dict:
        # num_classes and image_size are baked into the synthetic arrays
        # too — changing either must re-prepare
        return dict(super()._synth_marker(),
                    num_classes=self._synthetic_num_classes,
                    image_size=self.image_size)

    def _prepare(self, download: bool = False) -> None:
        train_root = os.path.join(self.dataset_dir, "train")
        if os.path.isdir(train_root):
            self._prepare_from_tree(train_root)
            return
        if not self._synthetic:
            raise FileNotFoundError(
                f"no train/ image tree under {self.dataset_dir} and "
                "synthetic=False")
        n = self._synthetic_num_classes
        self.num_classes = n
        train_images, train_targets = _synthetic_cifar(
            n, self._synthetic_per_class, img_hw=self.image_size)
        test_images, test_targets = _synthetic_cifar(
            n, max(self._synthetic_per_class // 4, 2),
            img_hw=self.image_size, seed=4321)
        os.makedirs(self.dataset_dir, exist_ok=True)
        images_per_client = []
        for c in range(n):
            sel = np.where(train_targets == c)[0]
            images_per_client.append(len(sel))
            np.save(self.client_fn(c), train_images[sel])
        np.savez(self.test_fn(), test_images=test_images,
                 test_targets=test_targets)
        self.write_stats(images_per_client, len(test_targets),
                         synthetic=self._synth_marker())

    def _prepare_from_tree(self, train_root: str) -> None:
        from PIL import Image  # lazy: PIL only needed for real preparation

        wnids = sorted(os.listdir(train_root))
        images_per_client = []
        sz = self.image_size
        for c, wnid in enumerate(wnids):
            files = sorted(os.listdir(os.path.join(train_root, wnid)))
            imgs = np.zeros((len(files), sz, sz, 3), np.uint8)
            for i, f in enumerate(files):
                im = Image.open(os.path.join(train_root, wnid, f))
                im = im.convert("RGB").resize((sz, sz))
                imgs[i] = np.asarray(im)
            np.save(self.client_fn(c), imgs)
            images_per_client.append(len(files))
        val_root = os.path.join(self.dataset_dir, "val")
        test_images, test_targets = [], []
        if os.path.isdir(val_root):
            for c, wnid in enumerate(sorted(os.listdir(val_root))):
                for f in sorted(os.listdir(os.path.join(val_root, wnid))):
                    im = Image.open(os.path.join(val_root, wnid, f))
                    test_images.append(
                        np.asarray(im.convert("RGB").resize((sz, sz))))
                    test_targets.append(c)
        test_images = (np.stack(test_images) if test_images
                       else np.zeros((0, sz, sz, 3), np.uint8))
        np.savez(self.test_fn(), test_images=test_images,
                 test_targets=np.asarray(test_targets, np.int64))
        self.write_stats(images_per_client,
                         len(test_targets))

    def _load_arrays(self) -> None:
        # client count may differ from the class attribute for synthetic trees
        self.num_classes = len(self.images_per_client)
        super()._load_arrays()
