"""Vectorized numpy augmentation/normalization stacks.

The reference composes per-item PIL/torchvision transforms inside DataLoader
worker processes (CommEfficient/data_utils/transforms.py:17-75). Here a
transform maps a whole batch dict of arrays at once — one vectorized pass on
the host per round, NHWC float32 out, ready for ``jax.device_put``.

Normalization constants are the standard dataset statistics, identical to
the reference's (transforms.py:13-15, 29-30, 44-45, 62-63).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2471, 0.2435, 0.2616], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4867, 0.4408], np.float32)
CIFAR100_STD = np.array([0.2675, 0.2565, 0.2761], np.float32)
FEMNIST_MEAN = np.array([0.9637], np.float32)
FEMNIST_STD = np.array([0.1597], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _normalize(images: np.ndarray, mean, std) -> np.ndarray:
    x = images.astype(np.float32)
    if np.issubdtype(images.dtype, np.integer):  # uint8-range sources
        x = x / 255.0
    return (x - mean) / std


def _random_crop_flip(images: np.ndarray, pad: int,
                      rng: np.random.Generator,
                      flip: bool = True,
                      pad_mode: str = "reflect") -> np.ndarray:
    """Per-image random shift crop (pad then crop back to original size) and
    horizontal flip, fully vectorized via one gather."""
    n, h, w = images.shape[:3]
    padded = np.pad(images,
                    [(0, 0), (pad, pad), (pad, pad)] +
                    [(0, 0)] * (images.ndim - 3),
                    mode=pad_mode)
    dy = rng.integers(0, 2 * pad + 1, size=n)
    dx = rng.integers(0, 2 * pad + 1, size=n)
    rows = dy[:, None] + np.arange(h)[None, :]          # (n, h)
    cols = dx[:, None] + np.arange(w)[None, :]          # (n, w)
    out = padded[np.arange(n)[:, None, None], rows[:, :, None],
                 cols[:, None, :]]
    if flip:
        do_flip = rng.random(n) < 0.5
        out[do_flip] = out[do_flip, :, ::-1]
    return out


class CifarTrain:
    """reflect-pad-4 random crop + horizontal flip + normalize
    (reference cifar10_train_transforms, transforms.py:17-22).

    ``gather_fused(images, idx)``: fused native gather+augment path (C++
    data-plane, native/fedloader.cpp) used by ``FedDataset.gather`` when the
    library is built; numerically equivalent augmentation family (same
    pad/flip/normalize), different RNG stream."""

    def __init__(self, mean=CIFAR10_MEAN, std=CIFAR10_STD, seed: int = 0):
        self.mean, self.std = mean, std
        self.rng = np.random.default_rng(seed)
        self._seed = seed
        self._calls = 0

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        img = batch["image"]
        shape = img.shape
        flat = img.reshape((-1,) + shape[-3:])
        flat = _random_crop_flip(flat, pad=4, rng=self.rng)
        out = dict(batch)
        out["image"] = _normalize(flat.reshape(shape), self.mean, self.std)
        return out

    def gather_fused(self, images: np.ndarray, idx: np.ndarray):
        from commefficient_tpu.data import native
        if images.dtype != np.uint8 or not native.available():
            return None
        self._calls += 1
        return native.gather_augment(
            images, idx, self.mean, self.std, pad=4, flip=True,
            seed=(self._seed << 20) + self._calls)


class CifarEval:
    def __init__(self, mean=CIFAR10_MEAN, std=CIFAR10_STD):
        self.mean, self.std = mean, std

    def __call__(self, batch):
        out = dict(batch)
        out["image"] = _normalize(batch["image"], self.mean, self.std)
        return out

    def gather_fused(self, images: np.ndarray, idx: np.ndarray):
        from commefficient_tpu.data import native
        if images.dtype != np.uint8 or not native.available():
            return None
        return native.gather_normalize(images, idx, self.mean, self.std)


class FemnistTrain:
    """constant-pad-2 random crop (fill=white) + normalize. The reference
    additionally applies RandomResizedCrop/RandomRotation (transforms.py:47-52)
    which need per-image resampling; the shift-crop captures the dominant
    augmentation while staying one vectorized gather."""

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)

    def __call__(self, batch):
        img = batch["image"]
        shape = img.shape
        flat = img.reshape((-1,) + shape[-3:])
        flat = _random_crop_flip(flat, pad=2, rng=self.rng, flip=False,
                                 pad_mode="edge")
        out = dict(batch)
        out["image"] = _normalize(flat.reshape(shape), FEMNIST_MEAN,
                                  FEMNIST_STD)
        return out


class FemnistEval:
    def __call__(self, batch):
        out = dict(batch)
        out["image"] = _normalize(batch["image"], FEMNIST_MEAN, FEMNIST_STD)
        return out


class ImagenetTrain:
    """random horizontal flip + normalize on pre-sized 224 crops. (The
    reference's RandomResizedCrop runs on variable-size JPEGs; our ImageNet
    store is pre-resized at prepare time — see fed_imagenet.py.)"""

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)

    def __call__(self, batch):
        img = batch["image"]
        shape = img.shape
        flat = img.reshape((-1,) + shape[-3:]).copy()
        do_flip = self.rng.random(flat.shape[0]) < 0.5
        flat[do_flip] = flat[do_flip, :, ::-1]
        out = dict(batch)
        out["image"] = _normalize(flat.reshape(shape), IMAGENET_MEAN,
                                  IMAGENET_STD)
        return out


class ImagenetEval:
    def __call__(self, batch):
        out = dict(batch)
        out["image"] = _normalize(batch["image"], IMAGENET_MEAN, IMAGENET_STD)
        return out


def transforms_for(dataset_name: str, train: bool, seed: int = 0):
    if dataset_name == "CIFAR10":
        return (CifarTrain(seed=seed) if train else CifarEval())
    if dataset_name == "CIFAR100":
        return (CifarTrain(CIFAR100_MEAN, CIFAR100_STD, seed=seed)
                if train else CifarEval(CIFAR100_MEAN, CIFAR100_STD))
    if dataset_name == "EMNIST":
        return FemnistTrain(seed=seed) if train else FemnistEval()
    if dataset_name == "ImageNet":
        return ImagenetTrain(seed=seed) if train else ImagenetEval()
    return None
