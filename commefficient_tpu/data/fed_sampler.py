"""Federated round scheduler with static shapes.

Re-design of the reference ``FedSampler`` (CommEfficient/data_utils/
fed_sampler.py:5-71), which yields variable-length flat index arrays that the
torch DataLoader turns into ragged batches. XLA needs static shapes, so each
round here is a fixed-size triple

    client_ids : (num_workers,)            int64
    idx        : (num_workers, B)          int64 flat dataset indices
    mask       : (num_workers, B)          bool validity

with B = ``local_batch_size`` (or ``max_client_batch`` for whole-client
``-1`` batches). Semantics preserved from the reference:

- data order is permuted *within* each client per epoch (fed_sampler.py:23-26);
- every round samples ``num_workers`` clients uniformly without replacement
  from the clients with data remaining (fed_sampler.py:34-45);
- each sampled client contributes up to B of its remaining items
  (fed_sampler.py:49-58); with ``local_batch_size == -1`` a client whose
  dataset exceeds ``max_client_batch`` contributes a chunk per round until
  exhausted (the reference would yield one unbounded batch — set
  ``max_client_batch`` >= the largest client for exact parity);
- iteration stops when every client is exhausted.

Deviation that matches the *driver* rather than the sampler: rounds with
fewer than ``num_workers`` non-exhausted clients are dropped, because the
reference driver skips exactly those batches (cv_train.py:205-219).
Underfull *per-client* batches are kept and masked (the reference driver
instead skips them for fixed batch sizes; masking trains on strictly more
data with identical weighting, since every aggregation is datum-weighted).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Optional

import numpy as np


class Round(NamedTuple):
    client_ids: np.ndarray  # (num_workers,)
    idx: np.ndarray         # (num_workers, B)
    mask: np.ndarray        # (num_workers, B)


def mask_blocked(rnd: Round, blocked) -> Round:
    """Mask quarantined clients out of a sampled round.

    ``blocked`` is a set/container of client ids currently benched by the
    quarantine ledger (core/quarantine.py). Their slots keep the static
    shapes the jitted round needs but contribute no data (mask all-False
    — the same slot-masking convention the scenario engine's partial
    participation uses). The original Round is never mutated: prefetched
    rounds (core/pipeline.py) are shared state, and the block decision is
    taken at DISPATCH time against the ledger's current view.
    """
    if not blocked:
        return rnd
    hit = np.fromiter((int(c) in blocked for c in rnd.client_ids),
                      dtype=bool, count=len(rnd.client_ids))
    if not hit.any():
        return rnd
    return rnd._replace(mask=rnd.mask & ~hit[:, None])


class FedSampler:
    def __init__(self, data_per_client: np.ndarray, num_workers: int,
                 local_batch_size: int, max_client_batch: int = 512,
                 seed: Optional[int] = None, drop_underfull: bool = True):
        self.data_per_client = np.asarray(data_per_client, dtype=np.int64)
        self.num_clients = len(self.data_per_client)
        self.num_workers = min(num_workers, self.num_clients)
        if local_batch_size == -1:
            self.batch = int(max_client_batch)
        else:
            self.batch = int(local_batch_size)
        self.rng = np.random.RandomState(seed)
        self.drop_underfull = drop_underfull
        self.offsets = np.concatenate(
            [[0], np.cumsum(self.data_per_client)[:-1]])

    def epoch_rounds(self) -> int:
        """Upper bound on rounds this epoch (exact when all clients are the
        same size); cf. reference ``steps_per_epoch`` (utils.py:315-321)."""
        per_client_rounds = -(-self.data_per_client // self.batch)
        return int(per_client_rounds.sum()) // self.num_workers

    def __iter__(self) -> Iterator[Round]:
        # fresh within-client permutations each epoch
        perms = [self.offsets[c] + self.rng.permutation(
            self.data_per_client[c]) for c in range(self.num_clients)]
        cursor = np.zeros(self.num_clients, dtype=np.int64)
        while True:
            remaining = self.data_per_client - cursor
            alive = np.where(remaining > 0)[0]
            if len(alive) == 0:
                return
            if len(alive) < self.num_workers and self.drop_underfull:
                return
            take_n = min(self.num_workers, len(alive))
            chosen = self.rng.choice(alive, take_n, replace=False)

            W, B = self.num_workers, self.batch
            client_ids = np.zeros(W, dtype=np.int64)
            idx = np.zeros((W, B), dtype=np.int64)
            mask = np.zeros((W, B), dtype=bool)
            for slot, c in enumerate(chosen):
                n = int(min(remaining[c], B))
                start = cursor[c]
                idx[slot, :n] = perms[c][start:start + n]
                mask[slot, :n] = True
                client_ids[slot] = c
                cursor[c] += n
            yield Round(client_ids, idx, mask)


class ValSampler:
    """Static-shape validation batching: (B,) index + mask chunks over the
    val set (reference shards val batches round-robin to workers,
    fed_aggregator.py:337-364 — here the jitted val step takes one chunk)."""

    def __init__(self, num_items: int, batch_size: int):
        self.num_items = num_items
        self.batch = int(batch_size)

    def __iter__(self):
        for start in range(0, self.num_items, self.batch):
            n = min(self.batch, self.num_items - start)
            # pad the final partial chunk by WRAPPING to the start of the
            # val set (not by repeating item 0): the mask excludes padding
            # from every metric either way, but batch-stat-normalized
            # models compute eval statistics over the WHOLE chunk — 240
            # copies of one image would dominate the final chunk's norm
            # statistics and distort the real items' predictions
            idx = np.arange(start, start + self.batch,
                            dtype=np.int64) % self.num_items
            mask = np.zeros(self.batch, dtype=bool)
            mask[:n] = True
            yield idx, mask

    def __len__(self):
        return -(-self.num_items // self.batch)
