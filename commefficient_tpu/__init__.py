"""CommEfficient-TPU: a TPU-native communication-efficient federated learning framework.

A from-scratch JAX/XLA re-design of the capabilities of Tzq2doc/CommEfficient
(reference layout documented in SURVEY.md). The reference simulates federated
clients with a parameter-server process, per-GPU worker processes, shared
memory and NCCL (reference: fed_aggregator.py, fed_worker.py). Here the whole
federated round is ONE functional SPMD program: clients are a sharded batch
axis on a `jax.sharding.Mesh`, aggregation is `psum`/`reduce_scatter` over
ICI, and all state lives in a `FedState` pytree that stays on device.

Subpackages
-----------
- ``ops``:      compression kernels (top-k, CountSketch), pytree flattening, clipping
- ``core``:     client step, server update rules, the jitted federated round
- ``parallel``: mesh construction, sharded round step, ring attention
- ``models``:   Flax models (ResNet family, Fixup variants, GPT-2 DoubleHeads)
- ``data``:     federated datasets / client samplers (static-shape, TPU-friendly)
- ``utils``:    schedules, loggers, timers
"""

__version__ = "0.1.0"

from commefficient_tpu.config import FedConfig  # noqa: F401
