"""CommEfficient-TPU: a TPU-native communication-efficient federated learning framework.

A from-scratch JAX/XLA re-design of the capabilities of Tzq2doc/CommEfficient
(reference layout documented in SURVEY.md). The reference simulates federated
clients with a parameter-server process, per-GPU worker processes, shared
memory and NCCL (reference: fed_aggregator.py, fed_worker.py). Here the whole
federated round is ONE functional SPMD program: clients are a sharded batch
axis on a `jax.sharding.Mesh`, aggregation is `psum`/`reduce_scatter` over
ICI, and all state lives in a `FedState` pytree that stays on device.

Subpackages
-----------
- ``ops``:      compression kernels (top-k, CountSketch), pytree flattening, clipping
- ``core``:     client step, server update rules, the jitted federated round
- ``parallel``: mesh construction, sharded round step, ring attention
- ``models``:   Flax models (ResNet family, Fixup variants, GPT-2 DoubleHeads)
- ``data``:     federated datasets / client samplers (static-shape, TPU-friendly)
- ``utils``:    schedules, loggers, timers
"""

import os as _os

# Honor a virtual-CPU-device request (JAX_PLATFORMS=cpu +
# --xla_force_host_platform_device_count) even when a TPU-plugin
# sitecustomize has already set jax_platforms at the config layer, which
# overrides the env var. Must run before any backend initializes; drivers,
# tests, and the multichip dry-run all rely on it.
if ("xla_force_host_platform_device_count"
        in _os.environ.get("XLA_FLAGS", "")
        and "cpu" in _os.environ.get("JAX_PLATFORMS", "")):
    import jax as _jax

    # honor the env var's full platform list, not a hardcoded "cpu"
    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

__version__ = "0.1.0"

from commefficient_tpu.config import FedConfig  # noqa: F401
