"""Version-portable jax surface.

The runtime targets the newest jax (top-level ``jax.shard_map`` with the
``check_vma`` kwarg) but must also run on the 0.4.x line, where the
function lives in ``jax.experimental.shard_map`` and the same kwarg is
named ``check_rep``. Resolve once at import time and translate the
kwarg in whichever direction the installed jax needs, so every call
site can use the modern spelling unconditionally.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.4.35 exports it at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - exercised on old jax only
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


def shard_map(f, **kwargs):
    """``jax.shard_map`` with ``check_vma``/``check_rep`` translated to
    whatever the installed jax accepts (they are the same knob; it was
    renamed when varying-manual-axes checking replaced rep checking)."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def _ensure_optimization_barrier_batching() -> None:
    """Register the vmap rule for ``lax.optimization_barrier`` on jax
    lines that lack it (0.4.x raises NotImplementedError — hit by the
    fused sketch encode's per-client vmap path, whose streaming encodes
    carry barrier-chained scheduling tokens; newer jax ships exactly
    this rule). The barrier is semantically the identity on each
    operand, so batching passes the batch dims straight through."""
    try:
        from jax._src.interpreters import batching
        from jax._src.lax.lax import optimization_barrier_p
    except ImportError:  # pragma: no cover - internals moved; newer jax
        return           # lines ship the rule anyway
    if optimization_barrier_p in batching.primitive_batchers:
        return

    def _rule(args, dims, **params):
        out = optimization_barrier_p.bind(*args, **params)
        return out, dims

    batching.primitive_batchers[optimization_barrier_p] = _rule


_ensure_optimization_barrier_batching()


def pcast(x, axis_name, to="varying"):
    """``lax.pcast`` where it exists; identity elsewhere. The call only
    exists to mark replicated values as device-varying for the vma
    checker — on jax lines without pcast there is no vma checker to
    satisfy (rep checking is simply disabled via check_rep=False), so
    the identity is the correct translation, not an approximation."""
    from jax import lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to=to)
    return x
