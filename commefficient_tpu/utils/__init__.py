from commefficient_tpu.utils.schedules import PiecewiseLinear, Exp, lr_schedule_for
from commefficient_tpu.utils.logging import (
    Logger,
    TableLogger,
    TSVLogger,
    Timer,
    make_logdir,
)
from commefficient_tpu.utils.misc import steps_per_epoch

__all__ = [
    "PiecewiseLinear",
    "Exp",
    "lr_schedule_for",
    "Logger",
    "TableLogger",
    "TSVLogger",
    "Timer",
    "make_logdir",
    "steps_per_epoch",
]
