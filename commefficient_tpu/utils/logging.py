"""Console/TSV loggers, wall-clock timer and run-directory naming.

Reference equivalents: ``Logger``/``TableLogger``/``TSVLogger``/``Timer``/
``make_logdir`` (CommEfficient/utils.py:14-99). Behavior preserved: the table
logger locks its column set on first append and prints fixed-width rows; the
TSV logger records ``epoch,hours,top1Accuracy``; ``make_logdir`` encodes the
run config into a timestamped directory under ``runs/``.
"""

from __future__ import annotations

import os
import time
from datetime import datetime
from typing import Dict, Iterable, Optional


class Logger:
    """print-passthrough logger with the stdlib logging method names."""

    def _emit(self, msg, args=None):
        print(msg.format(args) if args is not None else msg)

    debug = info = warn = warning = error = critical = _emit


class TableLogger:
    """Fixed-width console table; columns fixed by the first row appended."""

    def __init__(self):
        self.keys: Optional[Iterable[str]] = None

    def append(self, output: Dict):
        if self.keys is None:
            self.keys = list(output.keys())
            print(*(f"{k:>12s}" for k in self.keys))
        row = []
        for k in self.keys:
            v = output[k]
            if isinstance(v, float):
                row.append(f"{v:12.4f}")
            else:
                row.append(f"{v!s:>12}")
        print(*row)


class TSVLogger:
    """Time-to-accuracy record: ``epoch,hours,top1Accuracy`` lines."""

    def __init__(self):
        self.log = ["epoch,hours,top1Accuracy"]

    def append(self, output: Dict):
        self.log.append("{},{:.8f},{:.2f}".format(
            output["epoch"], output["total_time"] / 3600,
            output["test_acc"] * 100))

    def __str__(self):
        return "\n".join(self.log)


class Timer:
    """Split timer: each call returns the delta since the previous call and
    (optionally) accumulates it into ``total_time``."""

    def __init__(self):
        self.times = [time.time()]
        self.total_time = 0.0

    def __call__(self, include_in_total: bool = True) -> float:
        self.times.append(time.time())
        delta = self.times[-1] - self.times[-2]
        if include_in_total:
            self.total_time += delta
        return delta


def make_logdir(cfg) -> str:
    """``runs/<timestamp>_<workers/clients>_<mode[...]>_[k...]`` — same
    config-encoding scheme as reference utils.py:51-64."""
    if cfg.mode == "sketch":
        sketch_str = f"{cfg.mode}: {cfg.num_rows} x {cfg.num_cols}"
    else:
        sketch_str = cfg.mode
    k_str = f"k: {cfg.k}" if cfg.mode in ("sketch", "true_topk",
                                          "local_topk") else ""
    clients = cfg.num_clients if cfg.num_clients is not None else "auto"
    stamp = datetime.now().strftime("%b%d_%H-%M-%S")
    return os.path.join(
        "runs", f"{stamp}_{cfg.num_workers}/{clients}_{sketch_str}_{k_str}")
