"""Learning-rate schedules.

Reference equivalents: ``PiecewiseLinear`` and ``Exp`` in
CommEfficient/utils.py:26-35, driven through ``LambdaLR`` by the drivers
(cv_train.py:394-404, gpt2_train.py:302-307). Here a schedule is simply a
callable ``epoch_float -> lr``; drivers evaluate it per round and pass the
scalar into the jitted step, so the schedule itself never needs to trace.

Both schedules are also expressible as pure-jnp functions of a traced step
(``as_jax``) for fully on-device training loops (``lax.scan`` over rounds).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class PiecewiseLinear:
    """Linear interpolation through (knot, value) pairs; clamps outside."""

    knots: Sequence[float]
    vals: Sequence[float]

    def __call__(self, t: float) -> float:
        return float(np.interp(t, self.knots, self.vals))

    def as_jax(self, t):
        return jnp.interp(t, jnp.asarray(self.knots, jnp.float32),
                          jnp.asarray(self.vals, jnp.float32))


@dataclasses.dataclass(frozen=True)
class Exp:
    """Linear warmup to ``amplitude`` then base-10 exponential decay with
    scale ``decay_len`` epochs."""

    warmup_epochs: float
    amplitude: float
    decay_len: float

    def __call__(self, t: float) -> float:
        if t < self.warmup_epochs:
            return float(np.interp(t, [0.0, self.warmup_epochs],
                                   [0.0, self.amplitude]))
        return float(self.amplitude
                     * 10.0 ** (-(t - self.warmup_epochs) / self.decay_len))

    def as_jax(self, t):
        warm = jnp.interp(t, jnp.asarray([0.0, self.warmup_epochs]),
                          jnp.asarray([0.0, self.amplitude]))
        decay = self.amplitude * 10.0 ** (-(t - self.warmup_epochs)
                                          / self.decay_len)
        return jnp.where(t < self.warmup_epochs, warm, decay)


def lr_schedule_for(cfg) -> PiecewiseLinear:
    """The drivers' default triangular schedule (reference cv_train.py:393-404):
    0 -> lr_scale at pivot_epoch -> 0 at num_epochs. The reference notes the
    cifar10_fast heritage uses knots [0, 5, 24] with vals [0, 0.4, 0]."""
    lr = cfg.lr_scale if cfg.lr_scale is not None else 0.4
    return PiecewiseLinear([0.0, cfg.pivot_epoch, float(cfg.num_epochs)],
                          [0.0, lr, 0.0])
