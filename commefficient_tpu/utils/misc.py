"""Small run-math helpers."""

from __future__ import annotations

import math


def steps_per_epoch(local_batch_size: int, dataset_len: int,
                    num_clients: int, num_workers: int) -> int:
    """Rounds per epoch (reference utils.py:315-321): with whole-client
    batches (``local_batch_size == -1``) an epoch is one pass over all
    clients, ``num_workers`` of them per round; otherwise it is the number of
    rounds needed to see every datum once at ``local_batch_size`` items per
    participating client."""
    if local_batch_size == -1:
        return num_clients // num_workers
    return math.ceil(dataset_len / (local_batch_size * num_workers))
