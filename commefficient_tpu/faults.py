"""Deterministic crash/kill fault injection for the crash-matrix harness.

The preemption/fault-tolerance layer (checkpoint fallback, telemetry
append-resume, the graceful SIGTERM drain) only earns trust if the
process actually DIES at the awkward moments — mid-checkpoint-write,
between a telemetry write and its flush, inside the async in-flight
pool — and the resumed run is then proven bit-identical. Timing-based
kills are unreproducible, so the kill-points are injected: hot-path
sites call :func:`maybe_fault` with a point name (and optionally the
current round/seq), and when the ``COMMEFFICIENT_FAULT`` environment
variable names that point the process dies *right there* via
``os._exit`` — no ``finally`` blocks, no atexit, no flushes: the
closest a test can get to ``kill -9`` while staying deterministic.

Spec grammar (one fault per process)::

    COMMEFFICIENT_FAULT=<action>:<point>[:<n>]

- ``action``: ``kill`` (``os._exit(137)``, the SIGKILL-alike) or
  ``sigterm`` (``os.kill(getpid(), SIGTERM)`` — exercises the graceful
  drain instead of dying; the handler decides what happens next).
- ``point``: one of :data:`FAULT_POINTS`.
- ``n`` (optional): only trigger when the site's counter argument
  equals ``n`` (e.g. global round 5, telemetry seq 12). A point
  without ``n`` triggers on the site's first visit.

Cost when unset: module import parses the env var ONCE; every
``maybe_fault`` call is then a single ``is None`` check.

``sigterm`` fires at most once per process (the second visit would
re-signal a handler that already drained). ``kill`` needs no such
guard — the process is gone.
"""

from __future__ import annotations

import os
import signal
import sys
from typing import Optional, Tuple

FAULT_POINTS = (
    "pre_round",            # driver loop, before the round dispatches
    "mid_round",            # after dispatch, before telemetry/accounting
    "mid_checkpoint_write",  # tmp file written, BEFORE os.replace
    "mid_telemetry_flush",  # half a JSONL line written, stream unflushed
    "async_pool",           # inside AsyncAggregator.step, pool populated
)
_ACTIONS = ("kill", "sigterm")
_ENV = "COMMEFFICIENT_FAULT"
KILL_EXIT_CODE = 137        # the 128+SIGKILL convention


def _parse(spec: Optional[str]
           ) -> Optional[Tuple[str, str, Optional[int]]]:
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"{_ENV}={spec!r}: expected <action>:<point>[:<n>]")
    action, point = parts[0], parts[1]
    if action not in _ACTIONS:
        raise ValueError(f"{_ENV}={spec!r}: action {action!r} not in "
                         f"{_ACTIONS}")
    if point not in FAULT_POINTS:
        raise ValueError(f"{_ENV}={spec!r}: point {point!r} not in "
                         f"{FAULT_POINTS}")
    n = int(parts[2]) if len(parts) == 3 else None
    return action, point, n


_SPEC = _parse(os.environ.get(_ENV))
_FIRED = False


def faults_enabled() -> bool:
    return _SPEC is not None


def set_fault(spec: Optional[str]) -> None:
    """Test hook: (re)arm the module from a spec string (None disarms).
    The env-var path calls the same parser at import."""
    global _SPEC, _FIRED
    _SPEC = _parse(spec)
    _FIRED = False


def fault_matches(point: str, n=None) -> bool:
    """Whether the armed fault targets this site visit (no side
    effects) — for sites that need to corrupt something BEFORE dying
    (the mid-telemetry partial-line write)."""
    if _SPEC is None or _FIRED:
        return False
    action, p, want = _SPEC
    if p != point:
        return False
    return want is None or (n is not None and int(n) == want)


def trigger(point: str) -> None:
    """Execute the armed fault's action at ``point`` (the caller has
    already matched via :func:`fault_matches` and staged any
    corruption). ``kill`` never returns."""
    global _FIRED
    action = _SPEC[0]
    _FIRED = True
    sys.stderr.write(f"FAULT INJECTED: {action} at {point}\n")
    sys.stderr.flush()
    if action == "kill":
        os._exit(KILL_EXIT_CODE)
    os.kill(os.getpid(), signal.SIGTERM)


def maybe_fault(point: str, n=None) -> None:
    """The one-line site hook: die (or self-SIGTERM) here when the armed
    fault names this point/visit."""
    if _SPEC is None:
        return
    if fault_matches(point, n):
        trigger(point)
