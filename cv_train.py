#!/usr/bin/env python
"""Entry point kept at the repo root for reference-invocation parity:
``python cv_train.py --mode sketch ...`` (reference CommEfficient/cv_train.py).
"""

from commefficient_tpu.cv_train import main

if __name__ == "__main__":
    main()
